"""Thread-safe blocking client for the coordination store.

Plays the role of the reference's ``EtcdClient``
(python/edl/discovery/etcd_client.py:52-257): get/put/range/delete,
put-if-absent transactions for rank racing, leases with keepalive, and
prefix watches — here push-based over one multiplexed connection instead of
etcd watch streams.

Fault behavior mirrors the reference's ``_handle_errors`` reconnect
decorator (etcd_client.py:40-50): on a broken connection the client
reconnects with backoff; in-flight requests fail with
``EdlConnectionError`` (callers retry idempotent ops); watches are resumed
from the last delivered revision, falling back to a synthetic ``resync``
event when the server's history no longer covers it.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
import queue
from typing import Callable, Dict, List, Optional, Tuple

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs.metrics import histogram as _histogram
from edl_tpu.rpc.wire import pack_frame, read_frame_blocking
from edl_tpu.store.kv import Event
from edl_tpu.utils.exceptions import (
    EdlCompactedError,
    EdlConnectionError,
    EdlStoreError,
    deserialize_exception,
)
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import split_endpoint
from edl_tpu.utils.retry import retry_call

logger = get_logger("store.client")

RESYNC = "resync"

_M_ROUNDTRIP = _histogram(
    "edl_store_client_roundtrip_seconds",
    "store request round-trip (send to response), by method",
)

_FP_CONNECT = _fault_point(
    "store.client.connect", "store dial: drop/partition (store looks down)"
)
_FP_REQUEST = _fault_point(
    "store.client.request",
    "one store RPC: delay, or drop/partition before send (a blip — the "
    "caller's EdlConnectionError retry path takes over)",
)


class Watch:
    """Handle for an active prefix watch. ``cancel()`` to stop.

    The watch id is assigned by the *client* (unique across the client's
    lifetime) and survives reconnects, so pushed events can never race the
    handler registration.
    """

    def __init__(self, client: "StoreClient", wid: int, prefix: str, callback) -> None:
        self._client = client
        self.wid = wid
        self.prefix = prefix
        self.callback = callback
        self.last_rev: Optional[int] = None  # None = live-only, no replay
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        self._client._cancel_watch(self)


class _Pending:
    __slots__ = ("done", "response")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.response: Optional[dict] = None


class StoreClient:
    def __init__(
        self,
        endpoint: str,
        timeout: float = 10.0,
        reconnect: bool = True,
    ) -> None:
        self._endpoint = endpoint
        self._timeout = timeout
        self._reconnect_enabled = reconnect
        self._ids = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._watches: Dict[int, Watch] = {}  # wid -> Watch
        self._closed = False
        self._event_queue: "queue.Queue" = queue.Queue()
        self._connect()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="edl-store-dispatch", daemon=True
        )
        self._dispatcher.start()

    # -- connection management --------------------------------------------

    def _connect(self) -> None:
        if _FP_CONNECT.armed:
            _FP_CONNECT.fire(endpoint=self._endpoint)  # ChaosDrop is an OSError
        ip, port = split_endpoint(self._endpoint)
        sock = socket.create_connection((ip, port), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        with self._state_lock:
            if self._closed:
                sock.close()
                raise EdlConnectionError("client closed")
            self._sock = sock
        receiver = threading.Thread(
            target=self._receive_loop, args=(sock,), name="edl-store-recv", daemon=True
        )
        receiver.start()

    def _receive_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = read_frame_blocking(sock)
                if "w" in frame:
                    self._event_queue.put(("events", frame["w"], frame["ev"]))
                else:
                    with self._state_lock:
                        pending = self._pending.pop(frame.get("i"), None)
                    if pending is not None:
                        pending.response = frame
                        pending.done.set()
        except (ConnectionError, OSError) as exc:
            self._on_disconnect(sock, exc)

    def _on_disconnect(self, sock: socket.socket, exc: Exception) -> None:
        with self._state_lock:
            if self._sock is not sock:
                return  # stale receiver from a previous connection
            self._sock = None
            dropped = list(self._pending.values())
            self._pending.clear()
        for pending in dropped:
            pending.done.set()  # response stays None -> EdlConnectionError
        try:
            sock.close()
        except OSError:
            pass
        if self._closed or not self._reconnect_enabled:
            return
        logger.warning("store connection lost (%s); reconnecting", exc)
        threading.Thread(
            target=self._reconnect_loop, name="edl-store-reconnect", daemon=True
        ).start()

    def _reconnect_loop(self) -> None:
        try:
            retry_call(
                self._connect,
                what="store.reconnect",
                retry_on=(OSError,),
                base_delay=0.1,
                max_delay=2.0,
                give_up=lambda: self._closed,
            )
        except OSError:
            return  # gave up: the client was closed mid-retry
        if self._closed:
            return
        logger.info("store connection re-established")
        with self._state_lock:
            watches = [w for w in self._watches.values() if not w.cancelled]
        for watch in watches:
            try:
                self._start_watch(watch, resume=True)
            except EdlConnectionError:
                # link died again mid-resume; the watch stays registered and
                # the next reconnect cycle retries the whole set
                logger.warning("connection lost resuming watch %s", watch.prefix)
                break
            except EdlStoreError as exc:
                logger.warning("failed to resume watch %s: %s", watch.prefix, exc)

    def close(self) -> None:
        with self._state_lock:
            self._closed = True
            sock, self._sock = self._sock, None
            dropped = list(self._pending.values())
            self._pending.clear()
        for pending in dropped:
            pending.done.set()  # fail fast instead of riding out the timeout
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._event_queue.put(None)

    # -- request plumbing --------------------------------------------------

    def request(self, method: str, timeout: Optional[float] = None, **params) -> dict:
        if _FP_REQUEST.armed:
            try:
                _FP_REQUEST.fire(method=method)
            except ConnectionError as exc:
                raise EdlConnectionError("chaos: %s" % exc) from exc
        rid = next(self._ids)
        payload = {"i": rid, "m": method}
        payload.update(params)
        pending = _Pending()
        t0 = time.monotonic()
        with self._state_lock:
            sock = self._sock
            if sock is None:
                raise EdlConnectionError("store not connected")
            self._pending[rid] = pending
        try:
            with self._send_lock:
                sock.sendall(pack_frame(payload))
        except OSError as exc:
            with self._state_lock:
                self._pending.pop(rid, None)
            self._on_disconnect(sock, exc)  # a dead send means a dead link
            raise EdlConnectionError("send failed: %s" % exc) from exc
        if not pending.done.wait(timeout if timeout is not None else self._timeout):
            with self._state_lock:
                self._pending.pop(rid, None)
            raise EdlConnectionError("store request %r timed out" % method)
        resp = pending.response
        if resp is None:
            raise EdlConnectionError("connection lost awaiting %r" % method)
        _M_ROUNDTRIP.observe(time.monotonic() - t0, method=method)
        if not resp.get("ok"):
            raise deserialize_exception(resp.get("err", {}))
        return resp

    def retrying(self, method: str, retries: int = 30, **params) -> dict:
        """Retry an idempotent request across reconnects."""
        return retry_call(
            lambda: self.request(method, **params),
            what="store.request",
            retry_on=(EdlConnectionError,),
            retries=max(0, retries - 1),
            base_delay=0.05,
            max_delay=1.0,
            give_up=lambda: self._closed,
        )

    # -- KV API ------------------------------------------------------------

    def put(self, key: str, value: bytes, lease: int = 0) -> int:
        return self.request("put", k=key, v=value, l=lease)["r"]

    def put_if_absent(
        self, key: str, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[bytes]]:
        resp = self.request("put_absent", k=key, v=value, l=lease)
        return resp["created"], resp.get("cur")

    def cas(self, key: str, expect_mod_rev: int, value: bytes, lease: int = 0) -> bool:
        return self.request("cas", k=key, er=expect_mod_rev, v=value, l=lease)["swapped"]

    def get(self, key: str) -> Optional[bytes]:
        return self.request("get", k=key)["v"]

    def get_with_rev(self, key: str) -> Tuple[Optional[bytes], int]:
        resp = self.request("get", k=key)
        return resp["v"], resp.get("mr", 0)

    def range(self, prefix: str) -> Tuple[List[Tuple[str, bytes, int, int]], int]:
        resp = self.request("range", p=prefix)
        return [tuple(kv) for kv in resp["kvs"]], resp["r"]

    def delete(self, key: str) -> bool:
        return self.request("del", k=key)["deleted"] > 0

    def delete_range(self, prefix: str) -> int:
        return self.request("del_range", p=prefix)["deleted"]

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        return self.request("lease_grant", ttl=ttl)["lease"]

    def lease_keepalive(self, lease: int) -> bool:
        return self.request("lease_keepalive", lease=lease)["alive"]

    def lease_revoke(self, lease: int) -> None:
        self.request("lease_revoke", lease=lease)

    # -- watches -----------------------------------------------------------

    def watch(
        self,
        prefix: str,
        callback: Callable[[List[Event]], None],
        start_rev: Optional[int] = None,
    ) -> Watch:
        """Watch a prefix; ``callback(events)`` runs on a dispatcher thread.

        ``start_rev`` replays history after that revision first (pair it
        with ``range()``'s returned revision for a gapless read-then-watch).
        After a reconnect the watch resumes from the last delivered
        revision; if the server compacted past it, the callback receives a
        single ``Event(type='resync', key=prefix, rev=current)`` and the
        consumer should re-read current state via ``range``.
        """
        watch = Watch(self, next(self._ids), prefix, callback)
        if start_rev is not None:
            watch.last_rev = start_rev
        with self._state_lock:
            self._watches[watch.wid] = watch
        try:
            self._start_watch(watch, resume=False)
        except EdlStoreError:
            with self._state_lock:
                self._watches.pop(watch.wid, None)
            raise
        return watch

    def _start_watch(self, watch: Watch, resume: bool) -> None:
        params = {"p": watch.prefix, "wid": watch.wid}
        if watch.last_rev is not None:
            params["r"] = watch.last_rev
        try:
            resp = self.request("watch", **params)
        except EdlCompactedError:
            # history compacted past our resume point: restart fresh and
            # hand the consumer a resync marker (delivered through the
            # dispatcher queue so callback ordering is preserved)
            resp = self.request("watch", p=watch.prefix, wid=watch.wid)
            self._event_queue.put(
                (
                    "events",
                    watch.wid,
                    [Event(RESYNC, watch.prefix, None, resp["r"]).to_wire()],
                )
            )
        # any backlog arrives as an ordered push frame; the dispatcher takes
        # the max, so advancing to the server's revision here is safe
        watch.last_rev = max(watch.last_rev or 0, resp["r"])

    def _cancel_watch(self, watch: Watch) -> None:
        with self._state_lock:
            self._watches.pop(watch.wid, None)
        try:
            self.request("unwatch", wid=watch.wid)
        except EdlStoreError:
            pass

    def _dispatch_loop(self) -> None:
        while True:
            item = self._event_queue.get()
            if item is None:
                return
            _, wid, raw_events = item
            with self._state_lock:
                watch = self._watches.get(wid)
            if watch is None or watch.cancelled:
                continue
            events = [Event.from_wire(d) for d in raw_events]
            if events:
                watch.last_rev = max(watch.last_rev or 0, events[-1].rev)
                try:
                    watch.callback(events)
                except Exception:  # noqa: BLE001 — a consumer bug must not kill dispatch
                    logger.exception("watch callback failed for %s", watch.prefix)


class LeaseKeeper:
    """Background keepalive for a lease; the liveness heartbeat primitive.

    Parity: the reference refreshes etcd leases from a refresher thread
    every ~ttl/3 and re-registers after transient death
    (python/edl/utils/register.py:120-129, discovery/register.py:57-76).
    ``on_lost`` fires if the lease expired server-side or the store stayed
    unreachable past the TTL — the owner must then re-register.
    """

    def __init__(
        self,
        client: StoreClient,
        lease: int,
        ttl: float,
        on_lost: Optional[Callable[[], None]] = None,
    ) -> None:
        self._client = client
        self.lease = lease
        self._ttl = ttl
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="edl-lease-keeper", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        interval = max(self._ttl / 3.0, 0.05)
        misses = 0
        while not self._stop.wait(interval):
            try:
                alive = self._client.lease_keepalive(self.lease)
                misses = 0
            except EdlConnectionError:
                misses += 1
                if misses * interval < self._ttl:
                    continue
                alive = False
            if not alive:
                logger.warning("lease %d lost", self.lease)
                if self._on_lost is not None:
                    self._on_lost()
                return

    def stop(self, revoke: bool = False) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        if revoke:
            try:
                self._client.lease_revoke(self.lease)
            except EdlStoreError:
                pass
