"""Thread-safe blocking client for the coordination store.

Plays the role of the reference's ``EtcdClient``
(python/edl/discovery/etcd_client.py:52-257): get/put/range/delete,
put-if-absent transactions for rank racing, leases with keepalive, and
prefix watches — here push-based over one multiplexed connection instead of
etcd watch streams.

Fault behavior mirrors the reference's ``_handle_errors`` reconnect
decorator (etcd_client.py:40-50): on a broken connection the client
reconnects with backoff; in-flight requests fail with
``EdlConnectionError`` (callers retry idempotent ops); watches are resumed
from the last delivered revision, falling back to a synthetic ``resync``
event when the server's history no longer covers it.

Control-plane HA (DESIGN.md "Control-plane HA"): the client accepts an
ORDERED endpoint list ("primary,standby,...", refreshed from the
``/store/endpoints/`` keyspace) and fails over through it — on
connection loss, on a standby's ``EdlNotPrimaryError``, on a fenced
store's ``EdlFencedError``, and on any response whose fencing epoch is
LOWER than one already seen (a resurrected stale primary that nobody
fenced yet). Watches ride every one of these the same way they ride a
reconnect: resume from the last delivered revision, resync when the new
primary's history can't cover the gap.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import socket
import threading
import time
import queue
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import trace as _obs_trace
from edl_tpu.obs.metrics import counter as _counter
from edl_tpu.obs.metrics import histogram as _histogram
from edl_tpu.rpc.wire import TC_FIELD, pack_frame, read_frame_blocking
from edl_tpu.store import replica as replica_mod
from edl_tpu.store import shard as shard_mod
from edl_tpu.store.kv import Event
from edl_tpu.utils.exceptions import (
    EdlCompactedError,
    EdlConnectionError,
    EdlFencedError,
    EdlNotPrimaryError,
    EdlStoreError,
    deserialize_exception,
)
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import split_endpoint
from edl_tpu.utils.retry import retry_call

logger = get_logger("store.client")

_M_FAILOVERS = _counter(
    "edl_store_client_failovers_total",
    "endpoint failovers (connection loss, standby bounce, stale epoch)",
)

# while healthy, re-read /store/endpoints/ this often (piggybacked on
# request traffic): a client must learn a standby's address BEFORE the
# primary dies — refresh-on-reconnect alone can't, its only dial
# candidate being the endpoint that just vanished
_ENDPOINT_REFRESH_S = 5.0

RESYNC = "resync"

_M_ROUNDTRIP = _histogram(
    "edl_store_client_roundtrip_seconds",
    "store request round-trip (send to response), by method",
)

_M_STANDBY_FALLTHROUGH = _counter(
    "edl_store_client_standby_fallthrough_total",
    "standby-mode reads answered by the primary instead (standby "
    "refused: lag past EDL_STORE_STANDBY_MAX_LAG, session floor not "
    "applied yet, bootstrap — or the read leg was down)",
)

_TC = _obs_trace.PROPAGATION

_FP_CONNECT = _fault_point(
    "store.client.connect", "store dial: drop/partition (store looks down)"
)
_FP_REQUEST = _fault_point(
    "store.client.request",
    "one store RPC: delay, or drop/partition before send (a blip — the "
    "caller's EdlConnectionError retry path takes over)",
)


class Watch:
    """Handle for an active prefix watch. ``cancel()`` to stop.

    The watch id is assigned by the *client* (unique across the client's
    lifetime) and survives reconnects, so pushed events can never race the
    handler registration.
    """

    def __init__(self, client: "StoreClient", wid: int, prefix: str, callback) -> None:
        self._client = client
        self.wid = wid
        self.prefix = prefix
        self.callback = callback
        self.last_rev: Optional[int] = None  # None = live-only, no replay
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        self._client._cancel_watch(self)


class _Pending:
    __slots__ = ("done", "response")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.response: Optional[dict] = None


_CLI_IDS = itertools.count(1)


class _OpTape:
    """Consistency history tape: one JSONL record per completed client
    op (ok or fail), riding the flight recorder's crash-safe segment
    discipline. The chaos plane's history checker
    (``edl_tpu/chaos/consistency.py``) replays these records to prove —
    or catch — stale reads, lost acked writes, non-monotonic session
    reads and watch gaps under fault schedules. Enabled per client
    (``op_tape_dir=...``) or per process (``EDL_STORE_OP_TAPE=<dir>``);
    disabled it costs one attribute load per request.

    Values are taped as short digests, never contents: the checker only
    needs identity (did THIS acked write come back), and probe payloads
    stay out of evidence bundles. One tape = one SESSION (``cid``): a
    standby read leg shares its owner's tape, so session-level
    guarantees (read-your-writes, monotonic reads) are checked across
    both connections — which is exactly where they can break.
    """

    OPS = ("get", "range", "put", "cas", "del", "del_range")
    _ROW_CAP = 128  # range rows taped per op; more sets trunc

    def __init__(self, directory: str) -> None:
        from edl_tpu.obs.events import FlightRecorder

        self.cid = uuid.uuid4().hex[:8]
        self._rec = FlightRecorder(directory, component="storeop-" + self.cid)
        self._seq = itertools.count(1)

    @staticmethod
    def digest(value) -> Optional[str]:
        if value is None:
            return None
        if isinstance(value, str):
            value = value.encode()
        return hashlib.md5(bytes(value)).hexdigest()[:12]

    def _base(self, client: "StoreClient", method, params, t0) -> dict:
        doc = {
            "cid": self.cid,
            "cli": client._tape_cli,
            "seq": next(self._seq),
            "op": method,
            "t0": t0,
            "served": "standby" if params.get("rm") == "s" else "leader",
        }
        if "k" in params:
            doc["k"] = params["k"]
        elif "p" in params:
            doc["p"] = params["p"]
        if "rev" in params:
            doc["pin"] = True  # explicit MVCC pin: deliberately old
        if "v" in params:
            doc["d"] = self.digest(params["v"])
        return doc

    def ok(self, client, method, params, resp, t0) -> None:
        doc = self._base(client, method, params, t0)
        doc["ok"] = True
        if "r" in resp:
            doc["r"] = resp["r"]
        if method == "get":
            doc["mr"] = resp.get("mr", 0)
            doc["d"] = self.digest(resp.get("v"))
        elif method == "range":
            rows = resp.get("kvs") or []
            doc["n"] = len(rows)
            doc["rows"] = [
                [k, mr, self.digest(v)]
                for k, v, mr, *_ in rows[: self._ROW_CAP]
            ]
            if len(rows) > self._ROW_CAP:
                doc["trunc"] = True
        elif method == "cas":
            doc["sw"] = bool(resp.get("swapped"))
        elif method in ("del", "del_range"):
            doc["nd"] = resp.get("deleted", 0)
        self._rec.record("store_op", **doc)

    def fail(self, client, method, params, exc, t0) -> None:
        doc = self._base(client, method, params, t0)
        doc["ok"] = False  # indeterminate: the op may or may not have landed
        doc["err"] = type(exc).__name__
        self._rec.record("store_op", **doc)

    def watch_start(self, client, wid: int, prefix: str, r0: int) -> None:
        self._rec.record(
            "store_watch", cid=self.cid, cli=client._tape_cli,
            wid=wid, p=prefix, r0=r0,
        )

    def watch_events(self, client, wid: int, events) -> None:
        self._rec.record(
            "store_watch_ev", cid=self.cid, cli=client._tape_cli, wid=wid,
            evs=[[e.type, e.key, e.rev] for e in events],
        )

    def close(self) -> None:
        self._rec.close()


class StoreClient:
    def __init__(
        self,
        endpoint: Union[str, Sequence[str]],
        timeout: float = 10.0,
        reconnect: bool = True,
        read_mode: str = "leader",
        op_tape_dir: Optional[str] = None,
    ) -> None:
        if read_mode not in ("leader", "standby"):
            raise ValueError(
                "read_mode must be 'leader' or 'standby', got %r" % read_mode
            )
        # consistency history tape (chaos/consistency.py). A standby read
        # leg arrives with its owner's tape already installed — one tape
        # per SESSION, not per connection.
        self._tape_cli = next(_CLI_IDS)
        if getattr(self, "_tape", None) is None:
            tape_dir = op_tape_dir or os.environ.get(
                "EDL_STORE_OP_TAPE", ""
            ).strip()
            self._tape: Optional[_OpTape] = (
                _OpTape(tape_dir) if tape_dir else None
            )
        self._endpoints = replica_mod.parse_endpoints(endpoint)
        if not self._endpoints:
            raise ValueError("StoreClient needs at least one endpoint")
        self._ep_i = 0
        self._epoch = 0  # highest fencing epoch seen on any response
        self._timeout = timeout
        self._reconnect_enabled = reconnect
        self._ids = itertools.count(1)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._watches: Dict[int, Watch] = {}  # wid -> Watch
        self._closed = False
        self._reconnecting = False
        self._renewer: Optional["_LeaseRenewer"] = None
        self._last_refresh = time.monotonic()
        # standby read serving (DESIGN.md "Consistency model"):
        # read_mode="standby" sends get/range/watch through a second
        # connection to a standby member, falling through to the primary
        # whenever the standby refuses (lag bound, session floor) or the
        # leg is down. _min_rev is the SESSION FLOOR — the highest
        # revision any response on this client reported — sent as the
        # read's "minr" so a standby can never answer below what this
        # session already observed (read-your-writes + monotonic reads).
        self.read_mode = read_mode
        self._min_rev = 0
        self._standby_leg_client: Optional["_StandbyLegClient"] = None
        self._leg_failed_at = 0.0
        self._leg_rot = 0  # rotated into the leg's candidate order
        self._leg_misses = 0  # consecutive fall-throughs; many = rebuild
        self._event_queue: "queue.Queue" = queue.Queue()
        self._connect()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="edl-store-dispatch", daemon=True
        )
        self._dispatcher.start()
        self._refresh_endpoints()

    @property
    def _endpoint(self) -> str:
        """The endpoint this client currently targets (logging, tests)."""
        with self._state_lock:
            return self._endpoints[self._ep_i % len(self._endpoints)]

    # -- connection management --------------------------------------------

    def _connect(self) -> None:
        """Dial the current endpoint, then the rest of the ordered list.
        The index sticks to whichever endpoint answered, so after a
        failover every new request lands on the promoted primary."""
        with self._state_lock:
            candidates = [
                self._endpoints[(self._ep_i + k) % len(self._endpoints)]
                for k in range(len(self._endpoints))
            ]
        last_exc: Optional[OSError] = None
        for endpoint in candidates:
            if _FP_CONNECT.armed:
                try:
                    _FP_CONNECT.fire(endpoint=endpoint)  # ChaosDrop is an OSError
                except OSError as exc:
                    last_exc = exc
                    continue
            ip, port = split_endpoint(endpoint)
            try:
                sock = socket.create_connection((ip, port), timeout=self._timeout)
            except OSError as exc:
                last_exc = exc
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            with self._state_lock:
                if self._closed:
                    sock.close()
                    raise EdlConnectionError("client closed")
                self._sock = sock
                if endpoint in self._endpoints:
                    self._ep_i = self._endpoints.index(endpoint)
            receiver = threading.Thread(
                target=self._receive_loop, args=(sock,),
                name="edl-store-recv", daemon=True,
            )
            receiver.start()
            return
        raise last_exc if last_exc is not None else OSError("no endpoints")

    def _receive_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = read_frame_blocking(sock)
                if "w" in frame:
                    self._event_queue.put(("events", frame["w"], frame["ev"]))
                elif "wb" in frame:
                    # batched fan-out: one frame carrying deliveries for
                    # several of this connection's watches (the server
                    # coalesces per-connection to cut frame rate)
                    for wid, evs in frame["wb"]:
                        self._event_queue.put(("events", wid, evs))
                else:
                    with self._state_lock:
                        pending = self._pending.pop(frame.get("i"), None)
                    if pending is not None:
                        pending.response = frame
                        pending.done.set()
        except (ConnectionError, OSError) as exc:
            self._on_disconnect(sock, exc)

    def _on_disconnect(
        self, sock: socket.socket, exc: Exception, advance: bool = False
    ) -> None:
        with self._state_lock:
            if self._sock is not sock:
                return  # stale receiver from a previous connection
            self._sock = None
            if advance:
                # the endpoint answered but cannot serve (standby, fenced,
                # stale epoch): start the next dial one slot further on.
                # Inside the stale-receiver guard, so concurrent failures
                # of one connection advance exactly once.
                self._ep_i = (self._ep_i + 1) % len(self._endpoints)
                _M_FAILOVERS.inc()
            dropped = list(self._pending.values())
            self._pending.clear()
        for pending in dropped:
            pending.done.set()  # response stays None -> EdlConnectionError
        try:
            sock.close()
        except OSError:
            pass
        if self._closed or not self._reconnect_enabled:
            return
        with self._state_lock:
            if self._reconnecting:
                return  # one reconnect owner at a time; it laps until healthy
            self._reconnecting = True
        logger.warning("store connection lost (%s); reconnecting", exc)
        threading.Thread(
            target=self._reconnect_loop, name="edl-store-reconnect", daemon=True
        ).start()

    def _reconnect_loop(self) -> None:
        """Re-dial until a SERVING member answers. One lap = connect
        (walking the endpoint ring) + resume watches + refresh the
        endpoint list; a lap that lands on a standby or a fenced store
        bounces (the failed request advanced the ring) and goes again —
        damped, so cycling the ring while a standby promotes doesn't
        spin."""
        while True:
            try:
                retry_call(
                    self._connect,
                    what="store.reconnect",
                    retry_on=(OSError,),
                    base_delay=0.1,
                    max_delay=2.0,
                    give_up=lambda: self._closed,
                )
            except (OSError, EdlConnectionError):
                with self._state_lock:
                    self._reconnecting = False
                return  # gave up: the client was closed mid-retry
            if self._closed:
                with self._state_lock:
                    self._reconnecting = False
                return
            logger.info("store connection re-established (%s)", self._endpoint)
            resumed = self._resume_watches()
            if resumed:
                self._refresh_endpoints()
            with self._state_lock:
                # exit only once a FULL resume pass landed on a live
                # socket — a bounced resume (standby, fence, injected
                # blip) laps even if the socket itself survived. The flag
                # clears under the same lock _on_disconnect consults, so
                # a disconnect racing this exit either sees a live socket
                # (and spawns a fresh owner when it kills it) or keeps
                # this owner lapping.
                if self._closed or (resumed and self._sock is not None):
                    self._reconnecting = False
                    return
            time.sleep(0.1)

    def _resume_watches(self) -> bool:
        with self._state_lock:
            watches = [w for w in self._watches.values() if not w.cancelled]
        for watch in watches:
            try:
                self._start_watch(watch, resume=True)
            except EdlConnectionError as exc:
                # link died again mid-resume — or this member can't serve
                # (standby/fenced: request() already advanced the ring);
                # the watch stays registered and the next lap retries the
                # whole set
                logger.warning(
                    "resume of watch %s bounced (%s)", watch.prefix, exc
                )
                return False
            except EdlStoreError as exc:
                logger.warning("failed to resume watch %s: %s", watch.prefix, exc)
        return True

    def _refresh_endpoints(self) -> None:
        """Refresh the ordered endpoint list from the connected member's
        ``/store/endpoints/`` keyspace (slot order = promotion order).
        Seed endpoints never drop off the end: a stale keyspace must not
        strand the client with no dial candidates. Best-effort."""
        self._last_refresh = time.monotonic()
        try:
            rows, _rev = self.range(replica_mod.ENDPOINTS_PREFIX)
        except EdlStoreError:
            return
        fresh = replica_mod.parse_endpoint_rows(rows)
        if not fresh:
            return
        with self._state_lock:
            current = self._endpoints[self._ep_i % len(self._endpoints)]
            merged = fresh + [e for e in self._endpoints if e not in fresh]
            self._endpoints = merged
            self._ep_i = (
                merged.index(current) if current in merged else 0
            )

    def close(self) -> None:
        with self._state_lock:
            self._closed = True
            sock, self._sock = self._sock, None
            dropped = list(self._pending.values())
            self._pending.clear()
            leg, self._standby_leg_client = self._standby_leg_client, None
        if leg is not None:
            leg.close()
        for pending in dropped:
            pending.done.set()  # fail fast instead of riding out the timeout
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._event_queue.put(None)
        if self._tape is not None:
            self._tape.close()  # idempotent: a leg shares its owner's tape

    # -- request plumbing --------------------------------------------------

    def request(self, method: str, timeout: Optional[float] = None, **params) -> dict:
        tape = self._tape
        if tape is None or method not in _OpTape.OPS:
            return self._request_raw(method, timeout, **params)
        t0 = time.time()
        try:
            resp = self._request_raw(method, timeout, **params)
        except Exception as exc:
            tape.fail(self, method, params, exc, t0)
            raise
        tape.ok(self, method, params, resp, t0)
        return resp

    def _request_raw(
        self, method: str, timeout: Optional[float] = None, **params
    ) -> dict:
        if _FP_REQUEST.armed:
            try:
                _FP_REQUEST.fire(method=method)
            except ConnectionError as exc:
                raise EdlConnectionError("chaos: %s" % exc) from exc
        rid = next(self._ids)
        payload = {"i": rid, "m": method}
        payload.update(params)
        # distributed tracing: stamp the caller's span into the frame so
        # the server's handling span is OUR child. Disarmed cost is one
        # attribute load per request (fault-point/counter discipline).
        if _TC.armed and TC_FIELD not in payload:
            tc = _obs_trace.inject()
            if tc is not None:
                payload[TC_FIELD] = tc
        pending = _Pending()
        t0 = time.monotonic()
        with self._state_lock:
            sock = self._sock
            if sock is None:
                raise EdlConnectionError("store not connected")
            self._pending[rid] = pending
        try:
            with self._send_lock:
                sock.sendall(pack_frame(payload))
        except OSError as exc:
            with self._state_lock:
                self._pending.pop(rid, None)
            self._on_disconnect(sock, exc)  # a dead send means a dead link
            raise EdlConnectionError("send failed: %s" % exc) from exc
        if not pending.done.wait(timeout if timeout is not None else self._timeout):
            with self._state_lock:
                self._pending.pop(rid, None)
            raise EdlConnectionError("store request %r timed out" % method)
        resp = pending.response
        if resp is None:
            raise EdlConnectionError("connection lost awaiting %r" % method)
        _M_ROUNDTRIP.observe(time.monotonic() - t0, method=method)
        # epoch fencing: every response carries the server's fencing
        # epoch. A LOWER epoch than one we've already seen identifies a
        # resurrected stale primary — refuse it and fail over, even if it
        # happily "served" the request.
        epoch = resp.get("e")
        if epoch is not None:
            with self._state_lock:
                known = self._epoch
                if epoch > known:
                    self._epoch = epoch
            if epoch < known:
                self._on_disconnect(
                    sock,
                    EdlFencedError("stale epoch %d < %d" % (epoch, known)),
                    advance=True,
                )
                raise EdlFencedError(
                    "store at %s answered with stale epoch %d (cluster is "
                    "at %d); failing over" % (self._endpoint, epoch, known)
                )
        if not resp.get("ok"):
            exc = deserialize_exception(resp.get("err", {}))
            if isinstance(exc, (EdlNotPrimaryError, EdlFencedError)):
                if (
                    params.get("rm") == "s"
                    and isinstance(exc, EdlNotPrimaryError)
                ):
                    # a standby-serving refusal (lag bound, session
                    # floor, bootstrap) is a routine fall-through, not a
                    # dead member: keep the link, the owner retries the
                    # read against the primary
                    raise exc
                # this member answered but cannot serve: advance to the
                # next endpoint so the retry (every caller of the Edl
                # retry family) lands on the primary
                self._on_disconnect(sock, exc, advance=True)
            raise exc
        self._note_rev(resp.get("r"))
        if (
            method != "range"  # the refresh's own request must not recurse
            and time.monotonic() - self._last_refresh > _ENDPOINT_REFRESH_S
        ):
            self._last_refresh = time.monotonic()
            threading.Thread(
                target=self._refresh_endpoints,
                name="edl-store-refresh", daemon=True,
            ).start()
        return resp

    def retrying(self, method: str, retries: int = 30, **params) -> dict:
        """Retry an idempotent request across reconnects."""
        return retry_call(
            lambda: self.request(method, **params),
            what="store.request",
            retry_on=(EdlConnectionError,),
            retries=max(0, retries - 1),
            base_delay=0.05,
            max_delay=1.0,
            give_up=lambda: self._closed,
        )

    # -- standby read leg (read_mode="standby") ----------------------------

    def _note_rev(self, r) -> None:
        """Raise the session floor: the highest revision any response on
        this session reported. Standby reads carry it as ``minr``."""
        if isinstance(r, int):
            with self._state_lock:
                if r > self._min_rev:
                    self._min_rev = r

    def _standby_leg(self) -> Optional["_StandbyLegClient"]:
        """The (lazily dialed) read-serving connection to a standby
        member. None when leader mode, no standby candidates exist, or
        the last dial failed recently (damped)."""
        if self.read_mode != "standby" or self._closed:
            return None
        with self._state_lock:
            if self._standby_leg_client is not None:
                return self._standby_leg_client
            if time.monotonic() - self._leg_failed_at < 2.0:
                return None
            primary = self._endpoints[self._ep_i % len(self._endpoints)]
            cands = [e for e in self._endpoints if e != primary]
            rot = self._leg_rot % len(cands) if cands else 0
        if not cands:
            return None
        cands = cands[rot:] + cands[:rot]
        try:
            leg = _StandbyLegClient(cands, self, self._timeout)
        except (OSError, EdlConnectionError):
            with self._state_lock:
                self._leg_failed_at = time.monotonic()
            return None
        with self._state_lock:
            if self._standby_leg_client is None and not self._closed:
                self._standby_leg_client = leg
                return leg
            keep = self._standby_leg_client
        leg.close()  # lost a concurrent dial race (or the client closed)
        return keep

    def _drop_leg(self, rotate: bool = False) -> None:
        with self._state_lock:
            leg, self._standby_leg_client = self._standby_leg_client, None
            self._leg_misses = 0
            if rotate:
                self._leg_rot += 1
        if leg is not None:
            leg.close()

    def _read(self, method: str, **params) -> dict:
        """get/range through the read path: standby mode tries the leg
        first and falls through to the primary on any refusal or leg
        fault — the contract is 'never worse than leader mode, at most
        one extra round-trip'."""
        if self.read_mode == "standby":
            leg = self._standby_leg()
            if leg is not None:
                try:
                    resp = leg.request(method, **params)
                    self._leg_misses = 0
                    return resp
                except EdlConnectionError:
                    self._drop_leg()  # dead leg: rebuilt (damped) next read
                except EdlStoreError:
                    # refused (lag / session floor / bootstrapping member):
                    # a member that refuses every read for a long stretch
                    # earns a rotation to the next standby candidate
                    self._leg_misses += 1
                    if self._leg_misses >= 32:
                        self._drop_leg(rotate=True)
                _M_STANDBY_FALLTHROUGH.inc()
            # the fall-through carries the session floor too: the leg may
            # have answered at the standby's APPLIED revision a beat
            # before the primary processed the ack that releases it — the
            # primary clamps its read up to ``minr`` so this session
            # never watches its own history rewind by one round-trip
            params.setdefault("minr", self._min_rev)
        return self.request(method, **params)

    # -- KV API ------------------------------------------------------------

    def put(self, key: str, value: bytes, lease: int = 0) -> int:
        return self.request("put", k=key, v=value, l=lease)["r"]

    def put_if_absent(
        self, key: str, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[bytes]]:
        resp = self.request("put_absent", k=key, v=value, l=lease)
        return resp["created"], resp.get("cur")

    def cas(self, key: str, expect_mod_rev: int, value: bytes, lease: int = 0) -> bool:
        return self.request("cas", k=key, er=expect_mod_rev, v=value, l=lease)["swapped"]

    def get(self, key: str, rev: Optional[int] = None) -> Optional[bytes]:
        params = {"k": key}
        if rev is not None:
            params["rev"] = rev  # MVCC pin: the key's state AS OF rev
        return self._read("get", **params)["v"]

    def get_with_rev(self, key: str) -> Tuple[Optional[bytes], int]:
        resp = self._read("get", k=key)
        return resp["v"], resp.get("mr", 0)

    def range(
        self, prefix: str, rev: Optional[int] = None
    ) -> Tuple[List[Tuple[str, bytes, int, int]], int]:
        params = {"p": prefix}
        if rev is not None:
            params["rev"] = rev  # snapshot-coherent: every row AS OF rev
        resp = self._read("range", **params)
        return [tuple(kv) for kv in resp["kvs"]], resp["r"]

    def delete(self, key: str) -> bool:
        return self.request("del", k=key)["deleted"] > 0

    def delete_range(self, prefix: str) -> int:
        return self.request("del_range", p=prefix)["deleted"]

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        return self.request("lease_grant", ttl=ttl)["lease"]

    def lease_keepalive(self, lease: int) -> bool:
        return self.request("lease_keepalive", lease=lease)["alive"]

    def lease_keepalive_batch(self, leases: Sequence[int]) -> List[bool]:
        """Renew many leases in ONE RPC (the renew coalescer's op): the
        per-lease keepalive stream was the client side's dominant
        control-plane QPS at scale."""
        resp = self.request("lease_renew_batch", ls=list(leases))
        return [bool(a) for a in resp["alive"]]

    def lease_revoke(self, lease: int) -> None:
        self.request("lease_revoke", lease=lease)

    def _lease_renewer(self) -> "_LeaseRenewer":
        """The per-client renew coalescer every LeaseKeeper registers
        with (lazily created; one thread and one batched RPC per tick
        for ALL of this client's leases)."""
        with self._state_lock:
            if self._renewer is None:
                self._renewer = _LeaseRenewer(self)
            return self._renewer

    # -- watches -----------------------------------------------------------

    def watch(
        self,
        prefix: str,
        callback: Callable[[List[Event]], None],
        start_rev: Optional[int] = None,
    ) -> Watch:
        """Watch a prefix; ``callback(events)`` runs on a dispatcher thread.

        ``start_rev`` replays history after that revision first (pair it
        with ``range()``'s returned revision for a gapless read-then-watch).
        After a reconnect the watch resumes from the last delivered
        revision; if the server compacted past it, the callback receives a
        single ``Event(type='resync', key=prefix, rev=current)`` and the
        consumer should re-read current state via ``range``.

        In standby read mode the whole watch — registration, fan-out,
        reconnect resume — rides the read leg: the standby pushes events
        at apply time (applied == released there), and a leg failover
        resumes from the last delivered revision like any reconnect.
        """
        if self.read_mode == "standby":
            leg = self._standby_leg()
            if leg is not None:
                try:
                    return leg.watch(prefix, callback, start_rev=start_rev)
                except EdlStoreError:
                    _M_STANDBY_FALLTHROUGH.inc()
        watch = Watch(self, next(self._ids), prefix, callback)
        if start_rev is not None:
            watch.last_rev = start_rev
        with self._state_lock:
            self._watches[watch.wid] = watch
        try:
            self._start_watch(watch, resume=False)
        except EdlStoreError:
            with self._state_lock:
                self._watches.pop(watch.wid, None)
            raise
        if self._tape is not None:
            # deliveries begin after start_rev when given, else after the
            # registration high-water mark — the gap checker's floor
            self._tape.watch_start(
                self, watch.wid, prefix,
                start_rev if start_rev is not None else (watch.last_rev or 0),
            )
        return watch

    def _start_watch(self, watch: Watch, resume: bool) -> None:
        params = {"p": watch.prefix, "wid": watch.wid}
        if watch.last_rev is not None:
            params["r"] = watch.last_rev
        try:
            resp = self.request("watch", **params)
        except EdlCompactedError:
            # history compacted past our resume point: restart fresh and
            # hand the consumer a resync marker (delivered through the
            # dispatcher queue so callback ordering is preserved)
            resp = self.request("watch", p=watch.prefix, wid=watch.wid)
            self._event_queue.put(
                (
                    "events",
                    watch.wid,
                    [Event(RESYNC, watch.prefix, None, resp["r"]).to_wire()],
                )
            )
        # any backlog arrives as an ordered push frame; the dispatcher takes
        # the max, so advancing to the server's revision here is safe
        watch.last_rev = max(watch.last_rev or 0, resp["r"])

    def _cancel_watch(self, watch: Watch) -> None:
        with self._state_lock:
            self._watches.pop(watch.wid, None)
        try:
            self.request("unwatch", wid=watch.wid)
        except EdlStoreError:
            pass

    def _dispatch_loop(self) -> None:
        while True:
            item = self._event_queue.get()
            if item is None:
                return
            _, wid, raw_events = item
            with self._state_lock:
                watch = self._watches.get(wid)
            if watch is None or watch.cancelled:
                continue
            events = [Event.from_wire(d) for d in raw_events]
            if events:
                watch.last_rev = max(watch.last_rev or 0, events[-1].rev)
                if self._tape is not None:
                    self._tape.watch_events(self, watch.wid, events)
                try:
                    watch.callback(events)
                except Exception:  # noqa: BLE001 — a consumer bug must not kill dispatch
                    logger.exception("watch callback failed for %s", watch.prefix)


class _StandbyLegClient(StoreClient):
    """The read-serving leg of a ``read_mode="standby"`` client: a plain
    StoreClient pointed at the standby members whose reads opt into
    standby serving ("rm": "s") and carry the OWNER's session floor
    ("minr"), so the standby refuses — and the owner falls through to
    the primary — rather than answer below anything this session already
    observed. Revisions it sees raise the owner's floor too: the session
    contract spans both legs. Against a server that predates these
    fields the opt-in is never honored (the standby keeps bouncing reads
    with EdlNotPrimaryError), so degradation is the plain fall-through
    path, not an error."""

    _READ_OPS = ("get", "range", "watch", "unwatch")

    def __init__(self, endpoints, owner: StoreClient, timeout: float) -> None:
        self._owner = owner  # before super(): dialing refreshes via range()
        self._tape = owner._tape  # one SESSION tape spans both legs
        super().__init__(endpoints, timeout=timeout, reconnect=True)

    def request(self, method: str, timeout: Optional[float] = None, **params) -> dict:
        if method in self._READ_OPS:
            params.setdefault("rm", "s")
            params.setdefault("minr", self._owner._min_rev)
        resp = super().request(method, timeout, **params)
        self._owner._note_rev(resp.get("r"))
        return resp


class _RenewEntry:
    __slots__ = ("lease", "ttl", "interval", "on_lost", "next_due", "missed_s")

    def __init__(self, lease: int, ttl: float, on_lost) -> None:
        self.lease = lease
        self.ttl = ttl
        self.interval = max(ttl / 3.0, 0.05)
        self.on_lost = on_lost
        self.next_due = time.monotonic() + self.interval
        self.missed_s = 0.0


class _LeaseRenewer:
    """One renew loop per client, coalescing EVERY registered lease's
    keepalive into a single batched ``lease_renew_batch`` RPC per tick.

    The pre-shard design ran one keepalive thread + one RPC stream per
    lease; with thousands of registrations per connection the renew
    stream alone dominated store QPS (PR 10's per-method
    ``edl_rpc_server_seconds`` made that measurable). Falls back to
    per-lease ``lease_keepalive`` against servers that predate the
    batch op (the native C++ twin)."""

    def __init__(self, client) -> None:
        self._client = client
        self._lock = threading.Lock()
        self._entries: Dict[int, _RenewEntry] = {}  # edl: guarded-by(_lock)
        self._wake = threading.Event()
        self._batch_ok = True  # flips off after an unknown-method error
        self._thread = threading.Thread(
            target=self._run, name="edl-lease-renewer", daemon=True
        )
        self._thread.start()

    def add(self, lease: int, ttl: float, on_lost) -> None:
        with self._lock:
            self._entries[lease] = _RenewEntry(lease, ttl, on_lost)
        self._wake.set()

    def remove(self, lease: int) -> None:
        with self._lock:
            self._entries.pop(lease, None)

    def _run(self) -> None:
        while not getattr(self._client, "_closed", False):
            now = time.monotonic()
            with self._lock:
                # coalescing is the point: when the soonest entry comes
                # due, sweep in everything due within a horizon of ~1/3
                # of its own interval — renewing slightly early is free
                # (keepalive just restarts the TTL window) and it phase-
                # locks staggered registrations into ONE batch per tick
                # instead of a per-entry drizzle of tiny RPCs
                due = [
                    e for e in self._entries.values()
                    if e.next_due <= now + e.interval / 3.0
                ]
                if due and not any(e.next_due <= now for e in due):
                    due = []
                next_due = min(
                    (e.next_due for e in self._entries.values()),
                    default=now + 0.5,
                )
            if due:
                self._renew(due, now)
                with self._lock:
                    next_due = min(
                        (e.next_due for e in self._entries.values()),
                        default=now + 0.5,
                    )
            self._wake.wait(timeout=min(0.5, max(0.02, next_due - time.monotonic())))
            self._wake.clear()

    def _renew(self, due: List[_RenewEntry], now: float) -> None:
        lost: List[_RenewEntry] = []
        try:
            if self._batch_ok:
                alive = self._client.lease_keepalive_batch(
                    [e.lease for e in due]
                )
            else:
                alive = [
                    self._client.lease_keepalive(e.lease) for e in due
                ]
        except EdlConnectionError:
            # unreachable store: misses accumulate per lease; a lease is
            # only declared lost once the store stayed away past its TTL
            for e in due:
                e.missed_s += e.interval
                e.next_due = now + e.interval
                if e.missed_s >= e.ttl:
                    lost.append(e)
        except EdlStoreError as exc:
            if "unknown method" in str(exc) and self._batch_ok:
                logger.info(
                    "store predates lease_renew_batch; renewing per-lease"
                )
                self._batch_ok = False
                for e in due:
                    e.next_due = now  # retry immediately, uncoalesced
                return
            for e in due:
                e.next_due = now + e.interval
        else:
            for e, ok in zip(due, alive):
                e.missed_s = 0.0
                e.next_due = now + e.interval
                if not ok:
                    lost.append(e)
        for e in lost:
            with self._lock:
                # stop() may have raced the renew: only report a loss
                # for a lease still registered
                if self._entries.pop(e.lease, None) is None:
                    continue
            logger.warning("lease %d lost", e.lease)
            if e.on_lost is not None:
                try:
                    e.on_lost()
                except Exception:  # noqa: BLE001 — owner bugs must not kill renew
                    logger.exception("on_lost callback failed for %d", e.lease)


class LeaseKeeper:
    """Background keepalive for a lease; the liveness heartbeat primitive.

    Parity: the reference refreshes etcd leases from a refresher thread
    every ~ttl/3 and re-registers after transient death
    (python/edl/utils/register.py:120-129, discovery/register.py:57-76).
    ``on_lost`` fires if the lease expired server-side or the store stayed
    unreachable past the TTL — the owner must then re-register.

    Renewal is COALESCED: every keeper of one client registers with the
    client's shared :class:`_LeaseRenewer`, which issues one batched
    renew RPC per tick instead of one keepalive stream per lease.
    """

    def __init__(
        self,
        client,
        lease: int,
        ttl: float,
        on_lost: Optional[Callable[[], None]] = None,
    ) -> None:
        self._client = client
        self.lease = lease
        self._ttl = ttl
        self._renewer = client._lease_renewer()
        self._renewer.add(lease, ttl, on_lost)

    def stop(self, revoke: bool = False) -> None:
        self._renewer.remove(self.lease)
        if revoke:
            try:
                self._client.lease_revoke(self.lease)
            except EdlStoreError:
                pass


class _ShardedWatch:
    """Handle for a fan-out watch spanning every shard."""

    def __init__(self, prefix: str, watches: List[Watch]) -> None:
        self.prefix = prefix
        self._watches = watches
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        for w in self._watches:
            w.cancel()


class _VLease:
    """A virtual lease: granted lazily, per shard, on first use. The
    registry's grant-then-put idiom cannot know which shard the key
    will route to, so the sharded client hands out a VIRTUAL id and
    realizes a real lease on each shard the id actually touches."""

    __slots__ = ("vid", "ttl", "real")

    def __init__(self, vid: int, ttl: float) -> None:
        self.vid = vid
        self.ttl = ttl
        self.real: Dict[str, int] = {}  # shard name -> real lease id


class ShardedStoreClient:
    """Routes the StoreClient API across a consistent-hash-partitioned
    shard fleet (DESIGN.md "Sharded control plane").

    - keys route by their first-two-component token on the ring
      (``shard.route_token``), so a service's keys — and its
      read-then-watch revision sequence — live on ONE shard;
    - ranges/watches whose prefix pins the token are single-shard
      passthroughs; shorter prefixes fan out to every shard and merge
      (fan-out ``range`` revisions are NOT watch-resumable — pass
      ``start_rev`` only with a token-pinned prefix);
    - leases are virtual: realized per shard on first key attach,
      renewed via one batched renew RPC per shard per tick;
    - each per-shard client keeps its own ordered endpoint list,
      failover lap, and fencing-epoch horizon — per-shard failover
      needs no shard-map update.

    Use :func:`connect_store` to build one from a seed endpoint: it
    reads the replicated ``/store/shards/`` map and returns a plain
    StoreClient when the deployment is unsharded.
    """

    def __init__(
        self,
        shards: Sequence[Tuple[str, Sequence[str]]],
        timeout: float = 10.0,
        reconnect: bool = True,
        seed: Optional[StoreClient] = None,
        read_mode: str = "leader",
        op_tape_dir: Optional[str] = None,
    ) -> None:
        from edl_tpu.discovery.consistent_hash import ConsistentHash

        if not shards:
            raise ValueError("ShardedStoreClient needs at least one shard")
        self._timeout = timeout
        self._closed = False
        self.read_mode = read_mode
        self._clients: Dict[str, StoreClient] = {}
        self._meta_name = shards[0][0]
        names = []
        for name, endpoints in shards:
            names.append(name)
            if (
                seed is not None
                and seed._endpoint in endpoints
                and seed.read_mode == read_mode
            ):
                self._clients[name] = seed
                seed = None
                continue
            self._clients[name] = StoreClient(
                endpoints, timeout=timeout, reconnect=reconnect,
                read_mode=read_mode, op_tape_dir=op_tape_dir,
            )
        if seed is not None:
            seed.close()  # the seed member is not in the map (stale seed)
        self._ring = ConsistentHash(names)
        self._lease_lock = threading.Lock()
        self._vleases: Dict[int, _VLease] = {}  # edl: guarded-by(_lease_lock)
        self._vids = itertools.count(1)
        self._renewer: Optional[_LeaseRenewer] = None
        self._state_lock = threading.Lock()  # _lease_renewer() shares the idiom

    # -- topology ----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self._clients)

    @property
    def shard_names(self) -> List[str]:
        return sorted(self._clients)

    @property
    def _endpoint(self) -> str:
        """The meta shard's current endpoint (logging, tests)."""
        return self._clients[self._meta_name]._endpoint

    def shard_of(self, key: str) -> str:
        token = shard_mod.route_token(key)
        if token is None:
            return self._meta_name
        return self._ring.get_node(token) or self._meta_name

    def client_for(self, name: str) -> StoreClient:
        return self._clients[name]

    def _route(self, key: str) -> Tuple[str, StoreClient]:
        name = self.shard_of(key)
        return name, self._clients[name]

    # -- request plumbing (retrying() parity with StoreClient) -------------

    def request(self, method: str, timeout: Optional[float] = None, **params) -> dict:
        if method in ("put", "put_absent", "cas"):
            name, client = self._route(params["k"])
            lease = params.get("l", 0)
            if lease:
                params = dict(params, l=self._real_lease(name, client, lease))
            return client.request(method, timeout, **params)
        if method in ("get", "del"):
            _, client = self._route(params["k"])
            return client.request(method, timeout, **params)
        if method == "range":
            rows, rev = self.range(params["p"])
            return {"ok": True, "kvs": [list(r) for r in rows], "r": rev}
        if method == "del_range":
            return {"ok": True, "deleted": self.delete_range(params["p"])}
        if method in ("ping", "state"):
            return self._clients[self._meta_name].request(
                method, timeout, **params
            )
        raise EdlStoreError(
            "method %r is not routable through a sharded client" % method
        )

    def retrying(self, method: str, retries: int = 30, **params) -> dict:
        """Retry an idempotent request across reconnects."""
        return retry_call(
            lambda: self.request(method, **params),
            what="store.request",
            retry_on=(EdlConnectionError,),
            retries=max(0, retries - 1),
            base_delay=0.05,
            max_delay=1.0,
            give_up=lambda: self._closed,
        )

    # -- KV API ------------------------------------------------------------

    def put(self, key: str, value: bytes, lease: int = 0) -> int:
        return self.request("put", k=key, v=value, l=lease)["r"]

    def put_if_absent(
        self, key: str, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[bytes]]:
        resp = self.request("put_absent", k=key, v=value, l=lease)
        return resp["created"], resp.get("cur")

    def cas(self, key: str, expect_mod_rev: int, value: bytes, lease: int = 0) -> bool:
        return self.request(
            "cas", k=key, er=expect_mod_rev, v=value, l=lease
        )["swapped"]

    def get(self, key: str, rev: Optional[int] = None) -> Optional[bytes]:
        # through the shard client's public get: the standby read leg
        # (read_mode="standby") only rides the read API, not raw request()
        _, client = self._route(key)
        return client.get(key, rev=rev)

    def get_with_rev(self, key: str) -> Tuple[Optional[bytes], int]:
        _, client = self._route(key)
        return client.get_with_rev(key)

    def range(
        self, prefix: str, rev: Optional[int] = None
    ) -> Tuple[List[Tuple[str, bytes, int, int]], int]:
        single, token = shard_mod.route_prefix(prefix)
        if single:
            client = (
                self._clients[self._meta_name] if token is None
                else self._route_token(token)
            )
            return client.range(prefix, rev=rev)
        if rev is not None:
            # shard revision sequences are independent: one pin cannot
            # mean the same instant on every shard (same rule as watch
            # resume below)
            raise ValueError(
                "rev= needs a token-pinned prefix: %r spans shards" % prefix
            )
        rows: List[Tuple[str, bytes, int, int]] = []
        rev = 0
        for client in self._clients.values():
            shard_rows, shard_rev = client.range(prefix)
            rows.extend(shard_rows)
            rev = max(rev, shard_rev)
        rows.sort(key=lambda r: r[0])
        # NOTE: a fan-out revision spans independent shard sequences —
        # it orders nothing and must not seed a watch resume
        return rows, rev

    def delete(self, key: str) -> bool:
        return self.request("del", k=key)["deleted"] > 0

    def delete_range(self, prefix: str) -> int:
        single, token = shard_mod.route_prefix(prefix)
        if single:
            client = (
                self._clients[self._meta_name] if token is None
                else self._route_token(token)
            )
            return client.delete_range(prefix)
        return sum(c.delete_range(prefix) for c in self._clients.values())

    def _route_token(self, token: str) -> StoreClient:
        name = self._ring.get_node(token) or self._meta_name
        return self._clients[name]

    # -- leases (virtual; see _VLease) -------------------------------------

    def lease_grant(self, ttl: float) -> int:
        vid = next(self._vids)
        with self._lease_lock:
            self._vleases[vid] = _VLease(vid, float(ttl))
        return vid

    def _real_lease(self, shard: str, client: StoreClient, vid: int) -> int:
        with self._lease_lock:
            entry = self._vleases.get(vid)
            if entry is None:
                raise EdlStoreError("lease %d not found" % vid)
            real = entry.real.get(shard)
            ttl = entry.ttl
        if real is not None:
            return real
        granted = client.lease_grant(ttl)  # network op OUTSIDE the lock
        with self._lease_lock:
            entry = self._vleases.get(vid)
            if entry is None:
                revoke = True  # revoked while we were granting
            else:
                real = entry.real.setdefault(shard, granted)
                revoke = real != granted  # lost a concurrent grant race
        if revoke:
            try:
                client.lease_revoke(granted)
            except EdlStoreError:
                pass
            if entry is None:
                raise EdlStoreError("lease %d not found" % vid)
        return real

    def _reals(self, vid: int) -> Optional[List[Tuple[str, int]]]:
        with self._lease_lock:
            entry = self._vleases.get(vid)
            if entry is None:
                return None
            return list(entry.real.items())

    def lease_keepalive(self, lease: int) -> bool:
        reals = self._reals(lease)
        if reals is None:
            return False
        # alive only if EVERY shard-local part is alive: a shard that
        # expired its part already deleted that shard's keys, and the
        # owner must re-register
        alive = all(
            self._clients[shard].lease_keepalive(real)
            for shard, real in reals
        )
        if not alive:
            self._forget_vlease(lease)
        return alive

    def _forget_vlease(self, vid: int) -> None:
        """A lease reported dead is forgotten: the owner re-registers
        with a fresh grant, and keeping the stale entry would both leak
        the dict (registration churn over days) and keep renewing dead
        real ids."""
        with self._lease_lock:
            self._vleases.pop(vid, None)

    def lease_keepalive_batch(self, leases: Sequence[int]) -> List[bool]:
        """One renew RPC per SHARD per tick, regardless of lease count.

        Per-shard fault isolation: an unreachable shard defers ITS
        leases (reported alive — they resolve for real once that shard
        answers again, and a promoted standby resets lease clocks
        anyway) instead of letting one shard's outage count misses
        against every lease on the healthy shards. Only when EVERY
        probed shard is unreachable does the call raise, so the
        renewer's whole-store-down TTL accounting still runs."""
        per_shard: Dict[str, List[Tuple[int, int]]] = {}
        alive = {}
        for vid in leases:
            reals = self._reals(vid)
            if reals is None:
                alive[vid] = False
                continue
            alive[vid] = True  # no realized parts yet = nothing to lose
            for shard, real in reals:
                per_shard.setdefault(shard, []).append((vid, real))
        errors = 0
        for shard, pairs in per_shard.items():
            client = self._clients[shard]
            try:
                oks = client.lease_keepalive_batch([r for _, r in pairs])
            except EdlConnectionError:
                errors += 1
                continue  # defer this shard's verdicts
            except EdlStoreError:
                try:
                    oks = [client.lease_keepalive(r) for _, r in pairs]
                except EdlConnectionError:
                    errors += 1
                    continue
            for (vid, _real), ok in zip(pairs, oks):
                alive[vid] = alive[vid] and bool(ok)
        if per_shard and errors == len(per_shard):
            raise EdlConnectionError(
                "no store shard reachable for lease renewal"
            )
        for vid, ok in alive.items():
            if not ok:
                self._forget_vlease(vid)
        return [alive[vid] for vid in leases]

    def lease_revoke(self, lease: int) -> None:
        with self._lease_lock:
            entry = self._vleases.pop(lease, None)
        if entry is None:
            return
        for shard, real in entry.real.items():
            try:
                self._clients[shard].lease_revoke(real)
            except EdlStoreError:
                pass

    def _lease_renewer(self) -> "_LeaseRenewer":
        with self._state_lock:
            if self._renewer is None:
                self._renewer = _LeaseRenewer(self)
            return self._renewer

    # -- watches -----------------------------------------------------------

    def watch(
        self,
        prefix: str,
        callback: Callable[[List[Event]], None],
        start_rev: Optional[int] = None,
    ):
        single, token = shard_mod.route_prefix(prefix)
        if single:
            client = (
                self._clients[self._meta_name] if token is None
                else self._route_token(token)
            )
            return client.watch(prefix, callback, start_rev=start_rev)
        if start_rev is not None:
            raise ValueError(
                "start_rev needs a token-pinned prefix: %r spans shards "
                "whose revision sequences are independent" % prefix
            )
        watches = [
            c.watch(prefix, callback) for c in self._clients.values()
        ]
        return _ShardedWatch(prefix, watches)

    def close(self) -> None:
        self._closed = True
        for client in self._clients.values():
            client.close()


def connect_store(
    endpoint: Union[str, Sequence[str]],
    timeout: float = 10.0,
    reconnect: bool = True,
    read_mode: str = "leader",
    op_tape_dir: Optional[str] = None,
):
    """Dial ``endpoint`` and return the right client for the deployment:
    a plain :class:`StoreClient` when the store is one replication group,
    a :class:`ShardedStoreClient` when a ``/store/shards/`` map (two or
    more shards) is published — topology discovery rides the same
    replicated keyspace mechanism as endpoint discovery.

    ``read_mode="standby"`` turns on standby read serving (per shard in
    a sharded deployment): see :class:`StoreClient`. ``op_tape_dir``
    arms the consistency history tape (chaos/consistency.py)."""
    client = StoreClient(
        endpoint, timeout=timeout, reconnect=reconnect, read_mode=read_mode,
        op_tape_dir=op_tape_dir,
    )
    try:
        # retried: a transient blip here must NOT silently decide the
        # topology — a worker that degrades to an unsharded client in a
        # sharded deployment pins every key to the seed shard and
        # becomes invisible to correctly-routed peers. A terminal
        # connection failure propagates to the caller like any dial
        # failure; only a server that genuinely cannot answer the map
        # read (no such thing today) falls back to unsharded.
        resp = client.retrying("range", retries=10, p=shard_mod.SHARDS_PREFIX)
        rows = [tuple(kv) for kv in resp["kvs"]]
    except EdlConnectionError:
        client.close()
        raise
    except EdlStoreError:
        return client  # can't read the map: behave exactly as before
    shards = shard_mod.parse_shard_rows(rows)
    if len(shards) <= 1:
        return client
    return ShardedStoreClient(
        shards, timeout=timeout, reconnect=reconnect, seed=client,
        read_mode=read_mode, op_tape_dir=op_tape_dir,
    )
