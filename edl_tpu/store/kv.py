"""The store's pure state machine: keys, revisions, leases, watch matching.

Semantics are etcd-shaped because that is what the reference's control plane
is written against (python/edl/discovery/etcd_client.py:40-257):

- every mutation gets a monotonically increasing ``revision``;
- a key may be attached to a *lease*; when the lease expires (TTL seconds
  without keepalive) all its keys are deleted — this is the liveness
  primitive behind registration/heartbeat (reference register.py:120-129);
- ``put_if_absent`` is the put-if-key-absent transaction used for rank
  racing (reference etcd_client.py:172-197 ``set_server_not_exists``);
- prefix watches receive every event with revision > start point, enabling
  push-based membership diffing (reference watcher.py polls at 1 Hz; we
  push instead).

Networking-free so it can be unit-tested directly and reused verbatim by
alternative frontends.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

PUT = "put"
DELETE = "del"


@dataclass(frozen=True)
class Event:
    type: str  # PUT | DELETE
    key: str
    value: Optional[bytes]
    rev: int
    lease: int = 0

    def to_wire(self) -> dict:
        return {
            "t": self.type,
            "k": self.key,
            "v": self.value,
            "r": self.rev,
            "l": self.lease,
        }

    @staticmethod
    def from_wire(d: dict) -> "Event":
        return Event(d["t"], d["k"], d.get("v"), d["r"], d.get("l", 0))


@dataclass
class _KeyValue:
    value: bytes
    create_rev: int
    mod_rev: int
    lease: int  # 0 = no lease


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: Set[str]


class StoreState:
    """In-memory KV with revisions, leases and an event history ring.

    The history ring lets watchers resume from a past revision after a
    reconnect without a full re-read (bounded; a too-old resume point
    raises so the client knows to re-range).
    """

    HISTORY_LIMIT = 200_000

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._rev = 0
        self._kvs: Dict[str, _KeyValue] = {}
        self._leases: Dict[int, _Lease] = {}
        self._next_lease = 1
        self._history: deque[Event] = deque(maxlen=self.HISTORY_LIMIT)
        self._first_hist_rev = 1  # revision of the oldest retained event
        # MVCC version chains: key -> append-only [(mod_rev, value, lease,
        # alive)] so reads can answer at a PAST revision (the released
        # horizon, a pinned snapshot rev). Each global revision adds
        # exactly one entry across all chains, so total retained versions
        # are bounded by the compaction span plus one live base per key.
        self._vers: Dict[str, List[Tuple[int, Optional[bytes], int, bool]]] = {}
        self._nvers = 0
        self._compact_rev = 0  # reads strictly below this raise (compacted)
        # fencing epoch: bumped (and persisted) whenever a standby
        # promotes itself; a response carrying a LOWER epoch than the
        # client has already seen identifies a stale, fenced-off primary
        self._epoch = 0

    # -- internals ---------------------------------------------------------

    def _next_rev(self) -> int:
        self._rev += 1
        return self._rev

    def _record(self, ev: Event) -> Event:
        if len(self._history) == self._history.maxlen:
            self._first_hist_rev = self._history[0].rev + 1
        self._history.append(ev)
        return ev

    def _note_version(
        self, key: str, rev: int, value: Optional[bytes], lease: int, alive: bool
    ) -> None:
        """Append one entry to a key's version chain. Guarded against
        replays (a journal applied twice must not fork the chain)."""
        chain = self._vers.get(key)
        if chain is None:
            chain = self._vers[key] = []
        if chain and chain[-1][0] >= rev:
            return
        chain.append((rev, value, lease, alive))
        self._nvers += 1

    @staticmethod
    def _version_at(
        chain: List[Tuple[int, Optional[bytes], int, bool]], rev: int
    ) -> Optional[Tuple[int, Optional[bytes], int, bool]]:
        """Newest chain entry with mod_rev <= rev (None if the key did
        not exist yet at ``rev``)."""
        lo, hi = 0, len(chain)
        while lo < hi:
            mid = (lo + hi) // 2
            if chain[mid][0] <= rev:
                lo = mid + 1
            else:
                hi = mid
        if lo == 0:
            return None
        return chain[lo - 1]

    def _attach_lease(self, key: str, lease: int) -> None:
        if lease:
            entry = self._leases.get(lease)
            if entry is None:
                raise KeyError("lease %d not found" % lease)
            entry.keys.add(key)

    def _detach_lease(self, key: str, lease: int) -> None:
        if lease and lease in self._leases:
            self._leases[lease].keys.discard(key)

    # -- KV operations -----------------------------------------------------

    @property
    def revision(self) -> int:
        return self._rev

    @property
    def epoch(self) -> int:
        return self._epoch

    def set_epoch(self, epoch: int) -> None:
        """Epochs only move forward (a promotion or a fence, never a rollback)."""
        self._epoch = max(self._epoch, int(epoch))

    @property
    def lease_count(self) -> int:
        return len(self._leases)

    def put(self, key: str, value: bytes, lease: int = 0) -> Event:
        if lease and lease not in self._leases:
            raise KeyError("lease %d not found" % lease)
        old = self._kvs.get(key)
        if old is not None and old.lease != lease:
            self._detach_lease(key, old.lease)
        self._attach_lease(key, lease)
        rev = self._next_rev()
        if old is None:
            self._kvs[key] = _KeyValue(value, rev, rev, lease)
        else:
            old.value, old.mod_rev, old.lease = value, rev, lease
        self._note_version(key, rev, value, lease, True)
        return self._record(Event(PUT, key, value, rev, lease))

    def put_if_absent(
        self, key: str, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[Event], Optional[bytes]]:
        """Returns (created, event_if_created, existing_value_if_not)."""
        cur = self._kvs.get(key)
        if cur is not None:
            return False, None, cur.value
        return True, self.put(key, value, lease), None

    def cas(
        self, key: str, expect_mod_rev: int, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[Event]]:
        """Compare-and-swap on mod revision; ``expect_mod_rev=0`` = absent."""
        cur = self._kvs.get(key)
        cur_rev = cur.mod_rev if cur is not None else 0
        if cur_rev != expect_mod_rev:
            return False, None
        return True, self.put(key, value, lease)

    def get(
        self, key: str, rev: Optional[int] = None
    ) -> Optional[Tuple[bytes, int, int]]:
        """Returns (value, mod_rev, lease) or None.

        ``rev`` pins the read to a past revision (MVCC): the answer is the
        key's state as of that revision. ``rev >= revision`` (or None) is
        the fast path straight off the live map. A pin below the
        compaction floor raises ``ValueError``.
        """
        if rev is None or rev >= self._rev:
            kv = self._kvs.get(key)
            if kv is None:
                return None
            return kv.value, kv.mod_rev, kv.lease
        self._check_compacted(rev)
        chain = self._vers.get(key)
        ver = self._version_at(chain, rev) if chain else None
        if ver is None or not ver[3]:
            return None
        return ver[1], ver[0], ver[2]

    def range(
        self, prefix: str, rev: Optional[int] = None
    ) -> Tuple[List[Tuple[str, bytes, int, int]], int]:
        """All (key, value, mod_rev, lease) under prefix + the revision
        the answer is AS OF (current, or the ``rev`` pin clamped to
        current). A pinned range is snapshot-coherent: every row reflects
        the same revision, regardless of writes racing the scan."""
        if rev is None or rev >= self._rev:
            items = [
                (k, kv.value, kv.mod_rev, kv.lease)
                for k, kv in sorted(self._kvs.items())
                if k.startswith(prefix)
            ]
            return items, self._rev
        self._check_compacted(rev)
        items = []
        for k in sorted(self._vers):
            if not k.startswith(prefix):
                continue
            ver = self._version_at(self._vers[k], rev)
            if ver is not None and ver[3]:
                items.append((k, ver[1], ver[0], ver[2]))
        return items, rev

    def delete(self, key: str) -> Optional[Event]:
        kv = self._kvs.pop(key, None)
        if kv is None:
            return None
        self._detach_lease(key, kv.lease)
        rev = self._next_rev()
        self._note_version(key, rev, None, 0, False)
        return self._record(Event(DELETE, key, None, rev))

    def delete_range(self, prefix: str) -> List[Event]:
        keys = [k for k in self._kvs if k.startswith(prefix)]
        return [ev for k in keys if (ev := self.delete(k)) is not None]

    # -- MVCC version chains -----------------------------------------------

    @property
    def compact_rev(self) -> int:
        """Oldest revision versioned reads can still answer at."""
        return self._compact_rev

    @property
    def version_count(self) -> int:
        """Retained MVCC versions across all chains (gauge feed)."""
        return self._nvers

    def _check_compacted(self, rev: int) -> None:
        if rev < self._compact_rev:
            raise ValueError(
                "revision %d compacted (oldest readable: %d)"
                % (rev, self._compact_rev)
            )

    def compact(self, horizon: int) -> int:
        """Drop versions no read will ever need again: keep everything
        newer than ``horizon`` plus, per key, the newest alive version
        at-or-below it (the base a read AT the horizon resolves to;
        a tombstone base is droppable — absent and compacted-away read
        the same). Returns how many versions were dropped. The horizon
        never regresses."""
        if horizon <= self._compact_rev:
            return 0
        horizon = min(horizon, self._rev)
        dropped = 0
        for key in list(self._vers):
            chain = self._vers[key]
            lo, hi = 0, len(chain)
            while lo < hi:  # first entry with mod_rev > horizon
                mid = (lo + hi) // 2
                if chain[mid][0] <= horizon:
                    lo = mid + 1
                else:
                    hi = mid
            keep_base = lo > 0 and chain[lo - 1][3]
            start = lo - 1 if keep_base else lo
            if start <= 0:
                continue
            dropped += start
            self._nvers -= start
            if start == len(chain):
                del self._vers[key]
            else:
                self._vers[key] = chain[start:]
        self._compact_rev = horizon
        return dropped

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        lease_id = self._next_lease
        self._next_lease += 1
        self._leases[lease_id] = _Lease(
            lease_id, ttl, self._clock() + ttl, set()
        )
        return lease_id

    def lease_keepalive(self, lease_id: int) -> bool:
        entry = self._leases.get(lease_id)
        if entry is None:
            return False
        entry.deadline = self._clock() + entry.ttl
        return True

    def lease_revoke(self, lease_id: int) -> List[Event]:
        entry = self._leases.pop(lease_id, None)
        if entry is None:
            return []
        return [
            ev for k in sorted(entry.keys) if (ev := self.delete(k)) is not None
        ]

    def expire_leases(self) -> List[Event]:
        """Delete keys of every lease whose deadline passed. Call regularly."""
        return self.expire_leases_with_ids()[0]

    def expire_leases_with_ids(self) -> Tuple[List[Event], List[int]]:
        """Like :meth:`expire_leases` but also reports WHICH leases died —
        durability needs the revocations journaled, not just the deletes
        (replaying only the deletes would resurrect the lease with a fresh
        TTL and let a partitioned owner keep heartbeating a registration
        the cluster already saw expire)."""
        now = self._clock()
        expired = [l.id for l in self._leases.values() if l.deadline <= now]
        events: List[Event] = []
        for lease_id in expired:
            events.extend(self.lease_revoke(lease_id))
        return events, expired

    def next_lease_deadline(self) -> Optional[float]:
        if not self._leases:
            return None
        return min(l.deadline for l in self._leases.values())

    def reset_lease_deadlines(self) -> int:
        """Give every lease a fresh ``now + ttl`` window; returns how many
        were reset. Used when a store that cannot know the keepalive
        history takes over liveness duty (recovery restart, standby
        promotion) — expiring immediately would kill every live
        registration at once."""
        now = self._clock()
        for lease in self._leases.values():
            lease.deadline = now + lease.ttl
        return len(self._leases)

    # -- durability (snapshot + journal replay) ----------------------------
    #
    # The reference survives control-plane restarts because etcd is an
    # external disk-persistent daemon (reference scripts/download_etcd.sh;
    # clients ride a bounce via the ``_handle_errors`` reconnect decorator,
    # etcd_client.py:40-50). The in-tree store earns the same property with
    # the C++ master's Save/Load pattern (native/master): full-state
    # snapshots plus a journal of every mutation since, replayed on boot.

    def to_snapshot(self) -> dict:
        """Full durable state. Lease deadlines are stored as TTLs — on
        restore every lease gets a fresh ``now + ttl`` grace window (the
        store can't know how long it was down; expiring immediately would
        kill every live registration at once)."""
        return {
            "rev": self._rev,
            "epoch": self._epoch,
            "next_lease": self._next_lease,
            "kvs": [
                [k, kv.value, kv.create_rev, kv.mod_rev, kv.lease]
                for k, kv in self._kvs.items()
            ],
            "leases": [[l.id, l.ttl] for l in self._leases.values()],
        }

    def load_snapshot(self, snap: dict) -> None:
        now = self._clock()
        self._rev = snap["rev"]
        self._epoch = int(snap.get("epoch", 0))  # pre-HA snapshots: epoch 0
        self._next_lease = snap["next_lease"]
        self._leases = {
            lid: _Lease(lid, ttl, now + ttl, set())
            for lid, ttl in snap["leases"]
        }
        self._kvs = {}
        self._vers = {}
        self._nvers = 0
        for k, value, create_rev, mod_rev, lease in snap["kvs"]:
            self._kvs[k] = _KeyValue(value, create_rev, mod_rev, lease)
            self._note_version(k, mod_rev, value, lease, True)
            if lease in self._leases:
                self._leases[lease].keys.add(k)
        # a snapshot carries only the live map: versions older than it
        # are gone, so versioned reads below the snapshot rev are
        # compacted by construction (journal replay rebuilds the suffix)
        self._compact_rev = self._rev
        self._mark_history_lost()

    def _mark_history_lost(self) -> None:
        """After a restore the event history is gone: any watch resuming
        from an older revision must get a compaction error (the client
        then re-ranges and resyncs)."""
        self._history.clear()
        self._first_hist_rev = self._rev + 1

    def apply_journal(self, entry: dict, record: bool = False) -> None:
        """Replay one journal entry. Events carry their ORIGINAL revisions
        so restored mod_revs equal what clients observed (a CAS taken
        before the restart must still match after it).

        ``record=True`` also appends events to the watch-history ring —
        the live-replication apply path, where a promoted standby must be
        able to resume client watches from pre-failover revisions (disk
        replay keeps ``record=False``: that history died with the
        process, and resuming watches must resync).
        """
        op = entry["op"]
        if op == "grant":
            lid, ttl = entry["id"], entry["ttl"]
            self._leases[lid] = _Lease(lid, ttl, self._clock() + ttl, set())
            self._next_lease = max(self._next_lease, lid + 1)
        elif op == "revoke":
            self._leases.pop(entry["id"], None)
        elif op == "epoch":
            self.set_epoch(entry["e"])
        elif op == "ev":
            ev = Event.from_wire(entry)
            self._rev = max(self._rev, ev.rev)
            if record:
                self._record(ev)
            if ev.type == PUT:
                old = self._kvs.get(ev.key)
                if old is not None and old.lease != ev.lease:
                    self._detach_lease(ev.key, old.lease)
                if ev.lease in self._leases:
                    self._leases[ev.lease].keys.add(ev.key)
                if old is None:
                    self._kvs[ev.key] = _KeyValue(ev.value, ev.rev, ev.rev, ev.lease)
                else:
                    old.value, old.mod_rev, old.lease = ev.value, ev.rev, ev.lease
                self._note_version(ev.key, ev.rev, ev.value, ev.lease, True)
            elif ev.type == DELETE:
                kv = self._kvs.pop(ev.key, None)
                if kv is not None:
                    self._detach_lease(ev.key, kv.lease)
                self._note_version(ev.key, ev.rev, None, 0, False)
        else:
            raise ValueError("unknown journal op %r" % op)

    # -- watch support -----------------------------------------------------

    def history_since(self, rev: int, prefix: str) -> List[Event]:
        """Events with revision > rev matching prefix.

        Raises ``ValueError`` if the history ring no longer covers ``rev``
        (client must re-range and restart the watch from the fresh revision).
        """
        if rev + 1 < self._first_hist_rev:
            raise ValueError(
                "revision %d compacted (oldest retained: %d)"
                % (rev, self._first_hist_rev)
            )
        return [
            ev for ev in self._history if ev.rev > rev and ev.key.startswith(prefix)
        ]
