"""The store's pure state machine: keys, revisions, leases, watch matching.

Semantics are etcd-shaped because that is what the reference's control plane
is written against (python/edl/discovery/etcd_client.py:40-257):

- every mutation gets a monotonically increasing ``revision``;
- a key may be attached to a *lease*; when the lease expires (TTL seconds
  without keepalive) all its keys are deleted — this is the liveness
  primitive behind registration/heartbeat (reference register.py:120-129);
- ``put_if_absent`` is the put-if-key-absent transaction used for rank
  racing (reference etcd_client.py:172-197 ``set_server_not_exists``);
- prefix watches receive every event with revision > start point, enabling
  push-based membership diffing (reference watcher.py polls at 1 Hz; we
  push instead).

Networking-free so it can be unit-tested directly and reused verbatim by
alternative frontends.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

PUT = "put"
DELETE = "del"


@dataclass(frozen=True)
class Event:
    type: str  # PUT | DELETE
    key: str
    value: Optional[bytes]
    rev: int
    lease: int = 0

    def to_wire(self) -> dict:
        return {
            "t": self.type,
            "k": self.key,
            "v": self.value,
            "r": self.rev,
            "l": self.lease,
        }

    @staticmethod
    def from_wire(d: dict) -> "Event":
        return Event(d["t"], d["k"], d.get("v"), d["r"], d.get("l", 0))


@dataclass
class _KeyValue:
    value: bytes
    create_rev: int
    mod_rev: int
    lease: int  # 0 = no lease


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: Set[str]


class StoreState:
    """In-memory KV with revisions, leases and an event history ring.

    The history ring lets watchers resume from a past revision after a
    reconnect without a full re-read (bounded; a too-old resume point
    raises so the client knows to re-range).
    """

    HISTORY_LIMIT = 200_000

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._rev = 0
        self._kvs: Dict[str, _KeyValue] = {}
        self._leases: Dict[int, _Lease] = {}
        self._next_lease = 1
        self._history: deque[Event] = deque(maxlen=self.HISTORY_LIMIT)
        self._first_hist_rev = 1  # revision of the oldest retained event

    # -- internals ---------------------------------------------------------

    def _next_rev(self) -> int:
        self._rev += 1
        return self._rev

    def _record(self, ev: Event) -> Event:
        if len(self._history) == self._history.maxlen:
            self._first_hist_rev = self._history[0].rev + 1
        self._history.append(ev)
        return ev

    def _attach_lease(self, key: str, lease: int) -> None:
        if lease:
            entry = self._leases.get(lease)
            if entry is None:
                raise KeyError("lease %d not found" % lease)
            entry.keys.add(key)

    def _detach_lease(self, key: str, lease: int) -> None:
        if lease and lease in self._leases:
            self._leases[lease].keys.discard(key)

    # -- KV operations -----------------------------------------------------

    @property
    def revision(self) -> int:
        return self._rev

    def put(self, key: str, value: bytes, lease: int = 0) -> Event:
        if lease and lease not in self._leases:
            raise KeyError("lease %d not found" % lease)
        old = self._kvs.get(key)
        if old is not None and old.lease != lease:
            self._detach_lease(key, old.lease)
        self._attach_lease(key, lease)
        rev = self._next_rev()
        if old is None:
            self._kvs[key] = _KeyValue(value, rev, rev, lease)
        else:
            old.value, old.mod_rev, old.lease = value, rev, lease
        return self._record(Event(PUT, key, value, rev, lease))

    def put_if_absent(
        self, key: str, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[Event], Optional[bytes]]:
        """Returns (created, event_if_created, existing_value_if_not)."""
        cur = self._kvs.get(key)
        if cur is not None:
            return False, None, cur.value
        return True, self.put(key, value, lease), None

    def cas(
        self, key: str, expect_mod_rev: int, value: bytes, lease: int = 0
    ) -> Tuple[bool, Optional[Event]]:
        """Compare-and-swap on mod revision; ``expect_mod_rev=0`` = absent."""
        cur = self._kvs.get(key)
        cur_rev = cur.mod_rev if cur is not None else 0
        if cur_rev != expect_mod_rev:
            return False, None
        return True, self.put(key, value, lease)

    def get(self, key: str) -> Optional[Tuple[bytes, int, int]]:
        """Returns (value, mod_rev, lease) or None."""
        kv = self._kvs.get(key)
        if kv is None:
            return None
        return kv.value, kv.mod_rev, kv.lease

    def range(self, prefix: str) -> Tuple[List[Tuple[str, bytes, int, int]], int]:
        """All (key, value, mod_rev, lease) under prefix + current revision."""
        items = [
            (k, kv.value, kv.mod_rev, kv.lease)
            for k, kv in sorted(self._kvs.items())
            if k.startswith(prefix)
        ]
        return items, self._rev

    def delete(self, key: str) -> Optional[Event]:
        kv = self._kvs.pop(key, None)
        if kv is None:
            return None
        self._detach_lease(key, kv.lease)
        return self._record(Event(DELETE, key, None, self._next_rev()))

    def delete_range(self, prefix: str) -> List[Event]:
        keys = [k for k in self._kvs if k.startswith(prefix)]
        return [ev for k in keys if (ev := self.delete(k)) is not None]

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        lease_id = self._next_lease
        self._next_lease += 1
        self._leases[lease_id] = _Lease(
            lease_id, ttl, self._clock() + ttl, set()
        )
        return lease_id

    def lease_keepalive(self, lease_id: int) -> bool:
        entry = self._leases.get(lease_id)
        if entry is None:
            return False
        entry.deadline = self._clock() + entry.ttl
        return True

    def lease_revoke(self, lease_id: int) -> List[Event]:
        entry = self._leases.pop(lease_id, None)
        if entry is None:
            return []
        return [
            ev for k in sorted(entry.keys) if (ev := self.delete(k)) is not None
        ]

    def expire_leases(self) -> List[Event]:
        """Delete keys of every lease whose deadline passed. Call regularly."""
        now = self._clock()
        expired = [l.id for l in self._leases.values() if l.deadline <= now]
        events: List[Event] = []
        for lease_id in expired:
            events.extend(self.lease_revoke(lease_id))
        return events

    def next_lease_deadline(self) -> Optional[float]:
        if not self._leases:
            return None
        return min(l.deadline for l in self._leases.values())

    # -- watch support -----------------------------------------------------

    def history_since(self, rev: int, prefix: str) -> List[Event]:
        """Events with revision > rev matching prefix.

        Raises ``ValueError`` if the history ring no longer covers ``rev``
        (client must re-range and restart the watch from the fresh revision).
        """
        if rev + 1 < self._first_hist_rev:
            raise ValueError(
                "revision %d compacted (oldest retained: %d)"
                % (rev, self._first_hist_rev)
            )
        return [
            ev for ev in self._history if ev.rev > rev and ev.key.startswith(prefix)
        ]
