"""Built-in coordination store: a lease/watch KV service.

The reference outsources coordination to external etcd (leases, put-if-
absent rank racing, prefix watches — python/edl/discovery/etcd_client.py)
and redis (TTL keys — python/edl/distill/redis/redis_store.py). edl_tpu
ships its own store instead so a TPU-VM job has zero external dependencies:

- ``StoreState``  — the pure in-memory state machine (keys, revisions,
  leases, watch fan-out), independently unit-testable.
- ``StoreServer`` — a single-threaded event-loop TCP server speaking the
  edl_tpu wire protocol (rpc/wire.py).
- ``StoreClient`` — thread-safe blocking client with watch push dispatch,
  automatic reconnect + watch resumption, and ordered-endpoint failover.
- ``replica``     — control-plane HA plumbing: the warm-standby
  replication protocol helpers and the fencing-epoch probes
  (DESIGN.md "Control-plane HA").

The native C++ twin lives in ``native/`` and speaks the same protocol.
"""

from edl_tpu.store.kv import Event, StoreState
from edl_tpu.store.client import (
    LeaseKeeper,
    ShardedStoreClient,
    StoreClient,
    connect_store,
)


def __getattr__(name):
    # Lazy so ``python -m edl_tpu.store.server`` doesn't pre-import the
    # server module through the package __init__ (runpy double-import).
    if name == "StoreServer":
        from edl_tpu.store.server import StoreServer

        return StoreServer
    raise AttributeError(name)


__all__ = [
    "Event",
    "StoreState",
    "StoreServer",
    "StoreClient",
    "ShardedStoreClient",
    "LeaseKeeper",
    "connect_store",
]
