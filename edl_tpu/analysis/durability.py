"""Durability pass: durable writes go tmp -> fsync -> atomic rename.

The PR-3/PR-8 bug class: a snapshot/cache/manifest written in place is
a torn file waiting for the next SIGKILL. In the modules that own
durable state (store, checkpoint, WAL/flight/series rings, the compile
cache exchange, model fetch) every file-creating write must follow the
pattern the repo's own WAL/snapshot code established:

1. write to a private tmp name in the destination directory,
2. flush + ``os.fsync`` the fd before closing,
3. ``os.replace``/``os.rename`` onto the final name.

Heuristics (per function):

- ``open(target, "w"/"wb"/"x")`` or ``os.open(..., O_WRONLY|O_CREAT)``
  where the target expression doesn't smell like a tmp file
  (``tmp``/``.part``/``mkstemp``) and the function never renames
  -> **error** (torn write).
- tmp + rename present but no ``fsync`` anywhere in the function (or
  in same-module helpers it calls) -> **warning** (rename persists the
  name, not the bytes).
- append-mode opens are exempt: the WAL/flight-ring appenders carry
  their own fsync discipline and torn *tails* are reader-skipped by
  design.

``# edl: durability-ok(<why>)`` on the open line or the ``def`` line
records a deliberate exception (e.g. an ephemeral debug artifact).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, ModuleSource, register_pass,
)

# modules that own durable state; everything else may scratch freely
DURABLE_SCOPE = re.compile(
    r"(^|/)store/"
    r"|(^|/)checkpoint/"
    r"|(^|/)data/checkpoint\.py$"
    r"|(^|/)obs/(events|trace|monitor)\.py$"
    r"|(^|/)train/aot\.py$"
    r"|(^|/)distill/fetch\.py$"
    r"|(^|/)chaos/plane\.py$"
)

_TMP_SMELL = re.compile(r"tmp|\.part|mkstemp|temp", re.IGNORECASE)


def _mode_of(call: ast.Call) -> Optional[str]:
    """String mode of an ``open()`` call, or None when non-literal."""
    mode_node: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return "r"
    try:
        val = ast.literal_eval(mode_node)
    except Exception:
        return None
    return val if isinstance(val, str) else None


def _os_open_flags(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    if len(call.args) >= 2:
        for node in ast.walk(call.args[1]):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif isinstance(node, ast.Name):
                names.add(node.id)
    return names


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class _FnScan(ast.NodeVisitor):
    def __init__(self) -> None:
        self.write_opens: List[ast.Call] = []   # creating, non-append
        self.renames = False
        self.fsyncs = False
        self.called_names: Set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        head = (
            f.value.id
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            else None
        )
        if attr == "open" and head in (None, "io", "gzip"):
            mode = _mode_of(node)
            if mode is not None and (
                "w" in mode or "x" in mode or "+" in mode
            ) and "a" not in mode:
                self.write_opens.append(node)
        elif head == "os" and attr == "open":
            flags = _os_open_flags(node)
            if (
                ("O_WRONLY" in flags or "O_RDWR" in flags or "O_CREAT" in flags)
                and "O_APPEND" not in flags
            ):
                self.write_opens.append(node)
        if attr in ("replace", "rename", "renames", "link"):
            self.renames = True
        if attr is not None and "fsync" in attr:
            self.fsyncs = True
        if isinstance(f, ast.Name):
            self.called_names.add(f.id)
        elif isinstance(f, ast.Attribute):
            self.called_names.add(f.attr)
        self.generic_visit(node)

    # nested defs belong to the same durability story
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _module_fn_index(mod: ModuleSource):
    fns = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
    return fns


def _scan_function(
    mod: ModuleSource, qual: str, node: ast.AST, fn_index
) -> List[Finding]:
    if mod.annotation_for(node, "durability-ok") is not None:
        return []
    scan = _FnScan()
    for stmt in node.body:
        scan.visit(stmt)
    if not scan.write_opens:
        return []
    # one helper level: "_fsync_dir(...)"-style wrappers count
    fsyncs = scan.renames and scan.fsyncs
    if scan.renames and not fsyncs:
        for name in scan.called_names:
            helper = fn_index.get(name)
            if helper is None or helper is node:
                continue
            sub = _FnScan()
            for stmt in helper.body:
                sub.visit(stmt)
            if sub.fsyncs:
                fsyncs = True
                break
    findings: List[Finding] = []
    occ = 0
    for call in scan.write_opens:
        if mod.annotation_on(call.lineno, "durability-ok"):
            continue
        target = call.args[0] if call.args else None
        tmpish = target is not None and bool(
            _TMP_SMELL.search(_unparse(target))
        )
        ident = "%s:write" % qual + ("" if occ == 0 else "#%d" % occ)
        occ += 1
        if not scan.renames and not tmpish:
            findings.append(Finding(
                "atomic-write", mod.relpath, call.lineno, "error",
                "%s writes %s in place (no tmp + atomic rename): a crash "
                "mid-write leaves a torn file; write a tmp name, fsync, "
                "then os.replace — or annotate with "
                "'# edl: durability-ok(<why>)'" % (
                    qual, _unparse(target) or "a file",
                ),
                ident,
            ))
        elif scan.renames and not fsyncs:
            findings.append(Finding(
                "atomic-write", mod.relpath, call.lineno, "warning",
                "%s renames a tmp file into place without fsync: the "
                "rename persists the *name*, not the bytes — fsync the "
                "fd (and ideally the dir) before os.replace" % qual,
                ident,
            ))
    return findings


@register_pass(
    "atomic-write",
    "durable-state modules must write via tmp + fsync + atomic rename",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.tree is None or not DURABLE_SCOPE.search(mod.relpath):
            continue
        fn_index = _module_fn_index(mod)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(
                    _scan_function(mod, node.name, node, fn_index)
                )
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        findings.extend(_scan_function(
                            mod, "%s.%s" % (node.name, sub.name), sub,
                            fn_index,
                        ))
    return findings
