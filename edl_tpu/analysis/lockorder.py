"""Lock-order pass: global acquisition-order cycles = potential deadlock.

The repo now runs dozens of cooperating locks across five server planes
(store shards, replication, dispatcher, cache exchange, checkpoint
replicas), and the only thing standing between them and an AB/BA hang
was review discipline. This pass consumes the interprocedural lock-set
engine (graph.LockFlow): every ``with`` acquisition observed while
other locks are held contributes a directed edge *held -> acquired* to
one global graph — including edges that only exist across a call
boundary (a locked method calling a helper that takes its own lock).

Findings:

- **cycle** (error): a strongly connected component of 2+ locks. Two
  locks with both AB and BA witnesses are reported as an inconsistent
  acquisition order with both sites; longer cycles list the full loop.
- **reacquire** (error): a non-reentrant ``threading.Lock`` acquired
  again while already held on the same path — deadlock, not a race.

``# edl: lock-order-ok(<why>)`` on the inner ``with`` line waives the
edge at the acquisition site (for deliberate designs, e.g. a leaf lock
only ever probed with ``acquire(timeout=...)`` elsewhere).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from edl_tpu.analysis.core import AnalysisContext, Finding, register_pass
from edl_tpu.analysis.graph import LockId, lock_flow, lock_qualname


def _lid_key(lid: LockId):
    return (lid[0], lid[1] or "", lid[2])


def _edge_key(pair):
    return (_lid_key(pair[0]), _lid_key(pair[1]))


def _sccs(nodes, edges) -> List[List[LockId]]:
    """Tarjan over the acquisition-order graph; iterative (the graph is
    tiny, but recursion depth must not depend on lock count)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Dict[LockId, bool] = {}
    stack: List[LockId] = []
    out: List[List[LockId]] = []
    counter = [0]
    succ: Dict[LockId, List[LockId]] = {}
    for a, b in edges:
        succ.setdefault(a, []).append(b)

    for start in nodes:
        if start in index:
            continue
        work = [(start, iter(succ.get(start, ())))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack[start] = True
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack[nxt] = True
                    work.append((nxt, iter(succ.get(nxt, ()))))
                    advanced = True
                    break
                if on_stack.get(nxt):
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    top = stack.pop()
                    on_stack[top] = False
                    comp.append(top)
                    if top == node:
                        break
                if len(comp) > 1:
                    out.append(comp)
    return out


def _edge_str(edge) -> str:
    return "%s -> %s at %s:%d (path %s; %s first held at %s)" % (
        lock_qualname(edge.held), lock_qualname(edge.acquired),
        edge.rel, edge.line, " -> ".join(edge.chain),
        lock_qualname(edge.held), edge.held_site,
    )


@register_pass(
    "lock-order",
    "the global lock-acquisition-order graph (interprocedural, via the "
    "call-graph-propagated lock-set) must be cycle-free",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    flow = lock_flow(ctx)
    findings: List[Finding] = []

    # self-reacquire of a non-reentrant Lock on one path
    for (a, b), edge in sorted(
        flow.order_edges.items(), key=lambda kv: _edge_key(kv[0])
    ):
        if a != b:
            continue
        findings.append(Finding(
            "lock-order", edge.rel, edge.line, "error",
            "non-reentrant Lock %s is re-acquired while already held "
            "(path %s) — this deadlocks the thread; use an RLock or "
            "restructure, or waive the inner site with "
            "'# edl: lock-order-ok(<why>)'" % (
                lock_qualname(a), " -> ".join(edge.chain),
            ),
            "reacquire:%s" % lock_qualname(a),
        ))

    edges = [(a, b) for (a, b) in flow.order_edges if a != b]
    nodes = sorted({n for e in edges for n in e}, key=_lid_key)
    for comp in _sccs(nodes, edges):
        comp_set = set(comp)
        witnesses = [
            flow.order_edges[(a, b)]
            for (a, b) in sorted(flow.order_edges, key=_edge_key)
            if a in comp_set and b in comp_set and a != b
        ]
        names = sorted(lock_qualname(l) for l in comp)
        first = witnesses[0]
        if len(comp) == 2:
            msg = (
                "inconsistent acquisition order between %s and %s "
                "(potential AB/BA deadlock): %s" % (
                    names[0], names[1],
                    "; ".join(_edge_str(w) for w in witnesses[:4]),
                )
            )
        else:
            msg = (
                "lock-acquisition-order cycle over %s (potential "
                "deadlock): %s" % (
                    ", ".join(names),
                    "; ".join(_edge_str(w) for w in witnesses[:6]),
                )
            )
        findings.append(Finding(
            "lock-order", first.rel, first.line, "error",
            msg + " — fix by imposing one global order, or waive a "
            "deliberate edge with '# edl: lock-order-ok(<why>)'",
            "cycle:%s" % "+".join(names),
        ))
    return findings
