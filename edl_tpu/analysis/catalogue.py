"""Catalogue-conformance passes.

DESIGN.md carries four hand-maintained catalogues (metrics, fault
points, monitor rules, and — new here — ``EDL_*`` env knobs). These
passes are the single implementation of the lints that used to live
scattered across tests/test_obs.py, test_chaos.py and test_monitor.py:
an artifact registered in code without a catalogue row is a dashboard
mystery / un-drillable fault / rule that can never fire, and fails CI.

- ``metric-naming``:    every registered metric matches
                        ``edl_<component>_<name>_<unit>``.
- ``metric-catalogue``: every registered metric (incl. bind_gauges
                        spec tuples) has a DESIGN.md row.
- ``fault-catalogue``:  every ``fault_point(...)`` is catalogued and
                        dotted-lowercase.
- ``rule-catalogue``:   every built-in monitor rule has a rule-table
                        row, watches a catalogued metric, and is
                        slug-named/unique.
- ``env-registry``:     every literal ``EDL_*`` env read cross-checks
                        against the generated knob catalogue between
                        the ``edl-lint:knob-catalogue`` markers —
                        unregistered knobs, near-miss typos,
                        conflicting defaults, and table drift all flag.

The knob table itself is *generated* (``edl-lint
--write-knob-catalogue``), so the docs can't rot: drift is a finding.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, register_pass,
)

KNOB_BEGIN = "<!-- edl-lint:knob-catalogue:begin -->"
KNOB_END = "<!-- edl-lint:knob-catalogue:end -->"

_BACKTICKED = "`%s`"
_FAULT_NAME_RE = re.compile(r"^[a-z0-9_.]+$")
_REQUIRED = "<required>"


# -- collectors (also used by the test wrappers) ------------------------------


def _memo(ctx: AnalysisContext, key: str, build):
    """Collector results are pure functions of the parsed module set;
    memoize them on ctx.cache so naming+catalogue passes (and the test
    wrappers sharing repo_context()) don't re-walk ~100 ASTs each."""
    if key not in ctx.cache:
        ctx.cache[key] = build()
    return ctx.cache[key]


def collect_metric_registrations(
    ctx: AnalysisContext,
) -> Dict[str, List[Tuple[str, int, str]]]:
    """metric name -> [(relpath, line, kind)] where kind is
    'direct' (counter/gauge/histogram call) or 'tuple' (bind_gauges
    spec-tuple head). Scans edl_tpu/ only, like the original lint."""
    return _memo(
        ctx, "metric_registrations",
        lambda: _collect_metric_registrations(ctx),
    )


def _collect_metric_registrations(ctx):
    from edl_tpu.obs.metrics import METRIC_NAME_RE

    out: Dict[str, List[Tuple[str, int, str]]] = {}
    for mod in ctx.modules:
        if mod.tree is None or not mod.relpath.startswith("edl_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                attr = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if (
                    attr in ("counter", "gauge", "histogram")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.setdefault(node.args[0].value, []).append(
                        (mod.relpath, node.lineno, "direct")
                    )
            elif isinstance(node, (ast.Tuple, ast.List)) and node.elts:
                head = node.elts[0]
                if (
                    len(node.elts) >= 2
                    and isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and head.value.startswith("edl_")
                    and METRIC_NAME_RE.match(head.value)
                ):
                    out.setdefault(head.value, []).append(
                        (mod.relpath, head.lineno, "tuple")
                    )
    return out


def collect_fault_points(
    ctx: AnalysisContext,
) -> Dict[str, List[Tuple[str, int]]]:
    return _memo(ctx, "fault_points", lambda: _collect_fault_points(ctx))


def _collect_fault_points(ctx):
    out: Dict[str, List[Tuple[str, int]]] = {}
    for mod in ctx.modules:
        if mod.tree is None or not mod.relpath.startswith("edl_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if (
                attr is not None
                and attr.lstrip("_") == "fault_point"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, []).append(
                    (mod.relpath, node.lineno)
                )
    return out


def collect_env_reads(
    ctx: AnalysisContext,
) -> Dict[str, List[Tuple[str, int, Optional[str]]]]:
    """knob -> [(relpath, line, default)] for every literal ``EDL_*``
    env *read*; default is the literal's repr, '<required>' for bare
    subscripts/membership tests, or None when non-literal."""
    return _memo(ctx, "env_reads", lambda: _collect_env_reads(ctx))


def _collect_env_reads(ctx):

    def lit(node: ast.AST) -> Optional[str]:
        try:
            return repr(ast.literal_eval(node))
        except Exception:
            return None

    out: Dict[str, List[Tuple[str, int, Optional[str]]]] = {}

    def note(name_node: ast.AST, mod, line: int, default: Optional[str]):
        if (
            isinstance(name_node, ast.Constant)
            and isinstance(name_node.value, str)
            and name_node.value.startswith("EDL_")
        ):
            out.setdefault(name_node.value, []).append(
                (mod.relpath, line, default)
            )

    def is_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os"
        ) or (isinstance(node, ast.Name) and node.id == "environ")

    for mod in ctx.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("get", "setdefault")
                    and is_environ(f.value)
                    and node.args
                ):
                    d = lit(node.args[1]) if len(node.args) > 1 else None
                    note(node.args[0], mod, node.lineno, d)
                elif (
                    isinstance(f, ast.Attribute) and f.attr == "getenv"
                    and isinstance(f.value, ast.Name) and f.value.id == "os"
                    and node.args
                ):
                    d = lit(node.args[1]) if len(node.args) > 1 else None
                    note(node.args[0], mod, node.lineno, d)
            elif isinstance(node, ast.Subscript) and is_environ(node.value):
                # plain store contexts are writes, not reads
                if isinstance(node.ctx, ast.Load):
                    note(node.slice, mod, node.lineno, _REQUIRED)
            elif isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
            ):
                if node.comparators and is_environ(node.comparators[0]):
                    note(node.left, mod, node.lineno, _REQUIRED)
    return out


# -- knob catalogue generation ------------------------------------------------


def _knob_rows(
    reads: Dict[str, List[Tuple[str, int, Optional[str]]]]
) -> List[Tuple[str, str, str]]:
    rows = []
    for knob, sites in sorted(reads.items()):
        defaults = sorted(
            {d for _, _, d in sites if d is not None and d != _REQUIRED}
        )
        if defaults:
            default = defaults[0] if len(defaults) == 1 else "CONFLICT"
        elif any(d == _REQUIRED for _, _, d in sites):
            default = "required"
        else:
            default = "unset"
        mods = sorted({
            rel[:-3].replace("/", ".") for rel, _, _ in sites
        })
        shown = ", ".join(mods[:4]) + (
            ", … +%d" % (len(mods) - 4) if len(mods) > 4 else ""
        )
        rows.append((knob, default, shown))
    return rows


def generate_knob_catalogue(ctx: AnalysisContext) -> str:
    """The full marker-delimited markdown block for DESIGN.md."""
    reads = collect_env_reads(ctx)
    lines = [
        KNOB_BEGIN,
        "<!-- generated by `python -m tools.edl_lint "
        "--write-knob-catalogue`; do not hand-edit rows -->",
        "",
        "| knob | default | read by |",
        "|---|---|---|",
    ]
    for knob, default, mods in _knob_rows(reads):
        lines.append("| `%s` | `%s` | %s |" % (knob, default, mods))
    lines.append("")
    lines.append(KNOB_END)
    return "\n".join(lines)


def extract_knob_block(design_text: str) -> Optional[str]:
    begin = design_text.find(KNOB_BEGIN)
    end = design_text.find(KNOB_END)
    if begin < 0 or end < 0 or end < begin:
        return None
    return design_text[begin:end + len(KNOB_END)]


def catalogued_knobs(design_text: str) -> Dict[str, str]:
    """knob -> default column, parsed from the marker block."""
    block = extract_knob_block(design_text)
    if block is None:
        return {}
    out = {}
    for m in re.finditer(
        r"^\|\s*`(EDL_[A-Z0-9_]*)`\s*\|\s*`([^`]*)`", block, re.MULTILINE
    ):
        out[m.group(1)] = m.group(2)
    return out


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    if abs(len(a) - len(b)) > cap:
        return cap
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(
                prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)
            ))
        if min(cur) >= cap:
            return cap
        prev = cur
    return min(prev[-1], cap)


# -- passes -------------------------------------------------------------------


@register_pass(
    "metric-naming",
    "registered metric names follow edl_<component>_<name>_<unit>",
)
def run_metric_naming(ctx: AnalysisContext) -> List[Finding]:
    from edl_tpu.obs.metrics import METRIC_NAME_RE

    findings = []
    for name, sites in sorted(collect_metric_registrations(ctx).items()):
        if METRIC_NAME_RE.match(name):
            continue
        direct = [s for s in sites if s[2] == "direct"]
        for rel, line, _ in direct:  # tuple heads are pre-filtered by shape
            findings.append(Finding(
                "metric-naming", rel, line, "error",
                "metric %r does not match the naming convention (%s)"
                % (name, METRIC_NAME_RE.pattern),
                "metric:%s" % name,
            ))
    return findings


@register_pass(
    "metric-catalogue",
    "every registered metric has a DESIGN.md catalogue row",
)
def run_metric_catalogue(ctx: AnalysisContext) -> List[Finding]:
    if not ctx.design_text:
        return []
    findings = []
    for name, sites in sorted(collect_metric_registrations(ctx).items()):
        if _BACKTICKED % name in ctx.design_text:
            continue
        rel, line, _ = sites[0]
        findings.append(Finding(
            "metric-catalogue", rel, line, "error",
            "metric `%s` has no row in the DESIGN.md metric catalogue"
            % name,
            "metric:%s" % name,
        ))
    return findings


@register_pass(
    "fault-catalogue",
    "every declared fault point is catalogued in DESIGN.md and "
    "dotted-lowercase",
)
def run_fault_catalogue(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    points = collect_fault_points(ctx)
    for name, sites in sorted(points.items()):
        rel, line = sites[0]
        if not _FAULT_NAME_RE.match(name):
            findings.append(Finding(
                "fault-catalogue", rel, line, "error",
                "fault point %r is not dotted-lowercase" % name,
                "shape:%s" % name,
            ))
        if name.startswith("test."):
            continue
        if ctx.design_text and _BACKTICKED % name not in ctx.design_text:
            findings.append(Finding(
                "fault-catalogue", rel, line, "error",
                "fault point `%s` has no row in the DESIGN.md chaos "
                "catalogue" % name,
                "fault:%s" % name,
            ))
    return findings


@register_pass(
    "rule-catalogue",
    "every built-in monitor rule is slug-named, documented, and watches "
    "a catalogued metric",
)
def run_rule_catalogue(ctx: AnalysisContext) -> List[Finding]:
    if not ctx.design_text:
        return []
    try:
        from edl_tpu.obs.monitor import builtin_rules
    except Exception as exc:  # pragma: no cover - import environment
        return [Finding(
            "rule-catalogue", "edl_tpu/obs/monitor.py", 1, "error",
            "cannot import builtin_rules: %s" % exc, "import",
        )]
    findings = []
    mon = "edl_tpu/obs/monitor.py"
    seen = set()
    for r in builtin_rules():
        if r.name in seen:
            findings.append(Finding(
                "rule-catalogue", mon, 1, "error",
                "duplicate built-in rule name %r" % r.name,
                "rule-dup:%s" % r.name,
            ))
        seen.add(r.name)
        if not re.match(r"^[a-z][a-z0-9-]*$", r.name):
            findings.append(Finding(
                "rule-catalogue", mon, 1, "error",
                "built-in rule %r is not slug-shaped" % r.name,
                "rule-shape:%s" % r.name,
            ))
        if _BACKTICKED % r.name not in ctx.design_text:
            findings.append(Finding(
                "rule-catalogue", mon, 1, "error",
                "built-in rule `%s` has no row in the DESIGN.md rule table"
                % r.name,
                "rule-row:%s" % r.name,
            ))
        if r.metric and _BACKTICKED % r.metric not in ctx.design_text:
            findings.append(Finding(
                "rule-catalogue", mon, 1, "error",
                "built-in rule `%s` watches `%s`, which has no DESIGN.md "
                "catalogue row — it can never fire against real exports"
                % (r.name, r.metric),
                "rule-metric:%s" % r.name,
            ))
    return findings


def _covers_default_scope(ctx: AnalysisContext) -> bool:
    """True when the context includes every module the knob catalogue
    is generated from (the edl_tpu/tools trees that exist at root). A
    path-narrowed run (``edl-lint edl_tpu/store``) sees only a subset
    of env reads, so registered-but-unread and table-drift conclusions
    would be spurious there."""
    from edl_tpu.analysis.core import discover_files

    expected: List[str] = []
    for sub in ("edl_tpu", "tools"):
        if (ctx.root / sub).exists():
            expected.extend(discover_files(ctx.root, (sub,)))
    return bool(expected) and set(expected) <= set(ctx.by_path)


@register_pass(
    "env-registry",
    "every literal EDL_* env read cross-checks against the DESIGN.md "
    "knob catalogue (unregistered / typo / conflicting default / drift)",
)
def run_env_registry(ctx: AnalysisContext) -> List[Finding]:
    if not ctx.design_text:
        return []
    findings: List[Finding] = []
    reads = collect_env_reads(ctx)
    registered = catalogued_knobs(ctx.design_text)
    if extract_knob_block(ctx.design_text) is None:
        return [Finding(
            "env-registry", "DESIGN.md", 1, "error",
            "DESIGN.md has no knob-catalogue markers (%s … %s); run "
            "python -m tools.edl_lint --write-knob-catalogue"
            % (KNOB_BEGIN, KNOB_END),
            "markers",
        )]

    for knob, sites in sorted(reads.items()):
        rel, line, _ = sites[0]
        if knob not in registered:
            near = [
                other for other in registered
                if _edit_distance(knob, other) <= 2
            ]
            if near and len(sites) == 1:
                findings.append(Finding(
                    "env-registry", rel, line, "error",
                    "env knob %s is read once and is not in the DESIGN.md "
                    "knob catalogue, but %s is — possible typo" % (
                        knob, " / ".join(sorted(near)[:3]),
                    ),
                    "typo:%s" % knob,
                ))
            else:
                findings.append(Finding(
                    "env-registry", rel, line, "error",
                    "env knob %s is not in the DESIGN.md knob catalogue; "
                    "run python -m tools.edl_lint --write-knob-catalogue"
                    % knob,
                    "unregistered:%s" % knob,
                ))
        defaults = sorted({
            (d, r) for r, _, d in sites if d is not None and d != _REQUIRED
        })
        uniq = sorted({d for d, _ in defaults})
        if len(uniq) > 1:
            findings.append(Finding(
                "env-registry", rel, line, "warning",
                "env knob %s is read with conflicting literal defaults: %s"
                % (
                    knob,
                    "; ".join(
                        "%s in %s" % (d, ", ".join(sorted(
                            r for dd, r in defaults if dd == d
                        )))
                        for d in uniq
                    ),
                ),
                "default-conflict:%s" % knob,
            ))
    # stale-row and drift conclusions need the FULL default scope: a
    # path-narrowed run hasn't seen every read site and must not claim
    # catalogued knobs are unread or the table is wrong
    if _covers_default_scope(ctx):
        for knob in sorted(registered):
            if knob not in reads:
                findings.append(Finding(
                    "env-registry", "DESIGN.md", 1, "warning",
                    "knob catalogue lists %s but nothing reads it any "
                    "more; regenerate with --write-knob-catalogue" % knob,
                    "stale:%s" % knob,
                ))
        # full-block drift (default/module columns included)
        current = extract_knob_block(ctx.design_text)
        generated = generate_knob_catalogue(ctx)
        if current is not None and current.strip() != generated.strip():
            findings.append(Finding(
                "env-registry", "DESIGN.md", 1, "error",
                "the DESIGN.md knob catalogue has drifted from the code; "
                "run python -m tools.edl_lint --write-knob-catalogue",
                "drift",
            ))
    return findings
