"""Jit-purity pass: functions handed to ``jax.jit`` must be pure.

XLA traces the function once and replays the compiled program: a
``time.time()`` reads trace-time, not run-time; ``os.environ`` pins the
tracing process's config into the executable; Python-level ``random``
bakes a single draw; mutating a global from inside the traced function
runs once per (re)trace, silently. All of these "work" on the first
call and corrupt behaviour exactly when elasticity causes a retrace on
a resized mesh — the worst possible moment to discover them.

Flags, inside any function passed to ``jax.jit(...)`` / ``jit(...)``,
used as ``@jax.jit``/``@partial(jax.jit, ...)`` decorator, or reached
one call level deep in the same module:

- wall-clock reads     ``time.time/monotonic/perf_counter/time_ns``
- python randomness    ``random.*``, ``np.random.*`` (``jax.random`` ok)
- env reads            ``os.environ[...]``, ``os.environ.get``,
                       ``os.getenv``
- global mutation      ``global X`` statements

``# edl: jit-ok(<why>)`` on the offending line (or the jit'd def line
for a blanket waiver) records a deliberate exception, e.g. a debug
callback that is explicitly host-side.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, ModuleSource, register_pass,
)

_TIME_FNS = {"time", "monotonic", "perf_counter", "time_ns",
             "monotonic_ns", "perf_counter_ns"}


def _is_jit_callee(f: ast.AST) -> bool:
    if isinstance(f, ast.Name) and f.id in ("jit", "pjit"):
        return True
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("jit", "pjit")
        and isinstance(f.value, ast.Name)
        and f.value.id == "jax"
    )


class _Impurity(ast.NodeVisitor):
    def __init__(self, mod: ModuleSource, qual: str) -> None:
        self.mod = mod
        self.qual = qual
        self.hits: List[Tuple[int, str, str]] = []  # (line, kind, what)
        self.local_calls: Set[str] = set()

    def _hit(self, line: int, kind: str, what: str) -> None:
        if self.mod.annotation_on(line, "jit-ok") is None:
            self.hits.append((line, kind, what))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            head, attr = f.value.id, f.attr
            if head == "time" and attr in _TIME_FNS:
                self._hit(node.lineno, "time", "time.%s()" % attr)
            elif head == "random":
                self._hit(node.lineno, "random", "random.%s()" % attr)
            elif head == "os" and attr == "getenv":
                self._hit(node.lineno, "env", "os.getenv()")
            elif attr == "get" and self._is_environ(f.value):
                self._hit(node.lineno, "env", "os.environ.get()")
        elif isinstance(f, ast.Attribute):
            # np.random.<x>() — value is Attribute(np.random)
            v = f.value
            if (
                isinstance(v, ast.Attribute)
                and v.attr == "random"
                and isinstance(v.value, ast.Name)
                and v.value.id in ("np", "numpy")
            ):
                self._hit(node.lineno, "random", "%s.random.%s()"
                          % (v.value.id, f.attr))
            if (
                v is not None and isinstance(v, ast.Attribute)
                and v.attr == "environ"
                and isinstance(v.value, ast.Name) and v.value.id == "os"
            ):
                self._hit(node.lineno, "env", "os.environ.%s()" % f.attr)
        elif isinstance(f, ast.Name):
            self.local_calls.add(f.id)
        self.generic_visit(node)

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ) or (isinstance(node, ast.Name) and node.id == "environ")

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._is_environ(node.value):
            self._hit(node.lineno, "env", "os.environ[...]")
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self._hit(node.lineno, "global",
                  "global %s mutation" % ", ".join(node.names))


def _scan_callable(
    mod: ModuleSource, node: ast.AST, qual: str,
    fn_scope: Dict[str, ast.AST],
) -> List[Tuple[int, str, str]]:
    if (
        not isinstance(node, ast.Lambda)
        and mod.annotation_for(node, "jit-ok") is not None
    ):
        return []
    scan = _Impurity(mod, qual)
    body = node.body if isinstance(node.body, list) else [node.body]
    for stmt in body:
        scan.visit(stmt)
    hits = list(scan.hits)
    # one level into same-scope helpers the traced fn calls
    for name in sorted(scan.local_calls):
        helper = fn_scope.get(name)
        if helper is None or helper is node:
            continue
        if mod.annotation_for(helper, "jit-ok") is not None:
            continue
        sub = _Impurity(mod, name)
        for stmt in helper.body:
            sub.visit(stmt)
        hits.extend(
            (ln, kind, "%s (in helper %s)" % (what, name))
            for ln, kind, what in sub.hits
        )
    return hits


def _scope_defs(body) -> Dict[str, ast.AST]:
    """Function defs that are *directly* in the given scope body (not
    nested inside inner defs or class bodies — a bare Name can never
    refer to a method, and a same-named def in an unrelated scope must
    not shadow the one actually in scope)."""
    out: Dict[str, ast.AST] = {}
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
            continue  # don't descend: its defs belong to an inner scope
        if isinstance(node, (ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


def _visible_defs(
    tree: ast.Module, node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Dict[str, ast.AST]:
    """Lexically visible function defs at ``node``: module scope first,
    then each enclosing function scope, innermost winning — so
    ``jax.jit(step)`` inside a factory resolves the factory's local
    ``step``, not a same-named def elsewhere in the module."""
    chain = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            chain.append(cur)
        cur = parents.get(cur)
    visible = dict(_scope_defs(tree.body))
    for scope in reversed(chain):
        visible.update(_scope_defs(scope.body))
    return visible


@register_pass(
    "jit-purity",
    "no wall-clock, randomness, env reads, or global mutation inside "
    "functions passed to jax.jit",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.tree is None or "jit" not in mod.text:
            continue
        parents = _parent_map(mod.tree)
        # (target node, name, defs lexically visible at the jit site —
        # also the scope the one-level helper lookup resolves against)
        targets: List[Tuple[ast.AST, str, Dict[str, ast.AST]]] = []
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            # decorator form
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    d = deco.func if isinstance(deco, ast.Call) else deco
                    if _is_jit_callee(d) or (
                        isinstance(deco, ast.Call)
                        and any(_is_jit_callee(a) for a in deco.args)
                    ):
                        if id(node) not in seen:
                            seen.add(id(node))
                            targets.append((
                                node, node.name,
                                _visible_defs(mod.tree, node, parents),
                            ))
            # call form: jax.jit(fn) / jit(lambda ...)
            if isinstance(node, ast.Call) and _is_jit_callee(node.func):
                if not node.args:
                    continue
                arg = node.args[0]
                visible = _visible_defs(mod.tree, node, parents)
                if isinstance(arg, ast.Lambda):
                    if id(arg) not in seen:
                        seen.add(id(arg))
                        targets.append((arg, "<lambda>", visible))
                elif isinstance(arg, ast.Name):
                    target = visible.get(arg.id)
                    if target is not None and id(target) not in seen:
                        seen.add(id(target))
                        targets.append((target, arg.id, visible))
        for node, name, fn_scope in targets:
            qual = "%s.%s" % (mod.dotted, name)
            for ln, kind, what in _scan_callable(mod, node, qual, fn_scope):
                findings.append(Finding(
                    "jit-purity", mod.relpath, ln, "error",
                    "%s is traced by jax.jit but reads/mutates host state: "
                    "%s — hoist it out of the traced function or annotate "
                    "the line with '# edl: jit-ok(<why>)'" % (qual, what),
                    "%s:%s" % (name, kind),
                ))
    return findings
