"""Donation pass: step-shaped jitted functions should donate their state.

An elastic trainer's step signature is ``(state, batch) -> (state', ...)``
with the old state dead the moment the new one exists. Without
``donate_argnums`` XLA must keep BOTH generations of parameters and
optimizer state resident across the step — the live high-water mark is a
full state-size above what the author believes, which is exactly the
margin the memory plane's fit gate budgets away. The runtime half of
this check lives in obs/memory.py (``edl_train_donation_dropped_total``
fires when a donated plan shows zero aliased bytes); this pass is the
compile-time half: it flags the jit site BEFORE the job ships.

Flags ``jax.jit(...)`` / ``jit(...)`` sites — call form, ``@jax.jit``
decorator form, and ``partial(jax.jit, ...)`` decorators — whose traced
function is *step-shaped*: its first parameter is state-like by name
(``state`` / ``train_state`` / ``opt_state`` / ``params`` / ``carry``,
prefixes included), and no ``donate_argnums`` / ``donate_argnames``
keyword is present at the jit site. A literal ``donate_argnums`` that
does NOT cover argument 0 (and a ``donate_argnames`` missing the
parameter's name) still flags; a non-literal donation expression gets
the benefit of the doubt.

``# edl: donate-ok(<why>)`` on the jit-call or def line records a
deliberate exception — e.g. a step whose caller genuinely reuses the
old state (rollback buffers, line search), or a grad-only function
that never produces a successor state.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, ModuleSource, register_pass,
)

# first-parameter names that read as "the training state": exact or as a
# '_'-separated prefix (state_dict-style locals like ``state0`` count;
# ``w``/``x``/``weights`` deliberately do NOT — grad-only math functions
# take those and donating them is usually wrong)
_STATE_NAMES = ("state", "train_state", "opt_state", "params", "carry")

_DONATE_KWS = ("donate_argnums", "donate_argnames")


def _is_jit_callee(f: ast.AST) -> bool:
    if isinstance(f, ast.Name) and f.id in ("jit", "pjit"):
        return True
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("jit", "pjit")
        and isinstance(f.value, ast.Name)
        and f.value.id == "jax"
    )


def _state_like(name: str) -> bool:
    base = name.lstrip("_")
    for s in _STATE_NAMES:
        if base == s or base.startswith(s + "_") or (
            base.startswith(s) and base[len(s):].isdigit()
        ):
            return True
    return False


def _first_param(fn: ast.AST) -> Optional[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return None
    pos = list(args.posonlyargs) + list(args.args)
    if not pos:
        return None
    first = pos[0]
    if first.arg in ("self", "cls") and len(pos) > 1:
        return None  # a method's state is the instance, not arg 0
    return first.arg


def _literal_ints(node: ast.AST) -> Optional[List[int]]:
    """Parse a literal int / tuple-or-list of ints; None = not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def _donation_covers(
    keywords: List[ast.keyword], param: str
) -> Optional[bool]:
    """Does the jit site's donation keyword cover argument 0 / ``param``?
    True/False for a literal verdict, None when no donation keyword is
    present at all (the interesting case — the author never considered
    it)."""
    verdict: Optional[bool] = None
    for kw in keywords:
        if kw.arg == "donate_argnums":
            nums = _literal_ints(kw.value)
            if nums is None:
                return True  # non-literal: benefit of the doubt
            verdict = bool(verdict) or (0 in nums)
        elif kw.arg == "donate_argnames":
            names = _literal_strs(kw.value)
            if names is None:
                return True
            verdict = bool(verdict) or (param in names)
    return verdict


def _jit_keywords(call: ast.Call) -> List[ast.keyword]:
    return list(call.keywords)


def run_on_module(mod: ModuleSource) -> List[Finding]:
    findings: List[Finding] = []
    # local defs by name, for call-form jax.jit(step) resolution — the
    # simple module-scope map is enough: step factories in this codebase
    # def the step right next to the jit call
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    sites: List[Tuple[int, str, Optional[bool], ast.AST]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_callee(deco):
                    # bare @jax.jit: no keywords at all
                    sites.append((node.lineno, node.name, None, node))
                elif isinstance(deco, ast.Call) and _is_jit_callee(deco.func):
                    param = _first_param(node) or ""
                    sites.append((
                        node.lineno, node.name,
                        _donation_covers(_jit_keywords(deco), param), node,
                    ))
                elif (
                    isinstance(deco, ast.Call)
                    and any(_is_jit_callee(a) for a in deco.args)
                ):
                    # @partial(jax.jit, ...): keywords live on partial
                    param = _first_param(node) or ""
                    sites.append((
                        node.lineno, node.name,
                        _donation_covers(_jit_keywords(deco), param), node,
                    ))
        elif isinstance(node, ast.Call) and _is_jit_callee(node.func):
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in defs:
                fn = defs[arg.id]
                param = _first_param(fn) or ""
                sites.append((
                    node.lineno, arg.id,
                    _donation_covers(_jit_keywords(node), param), fn,
                ))

    seen = set()
    for line, name, covered, fn in sites:
        param = _first_param(fn)
        if param is None or not _state_like(param):
            continue
        if covered is True:
            continue
        key = (line, name)
        if key in seen:
            continue
        seen.add(key)
        if (
            mod.annotation_at(line, "donate-ok") is not None
            or mod.annotation_for(fn, "donate-ok") is not None
        ):
            continue
        what = (
            "donate_argnums does not cover it"
            if covered is False else "no donate_argnums"
        )
        findings.append(Finding(
            "donation", mod.relpath, line, "error",
            "%s.%s is step-shaped (first arg %r is the state) but the jit "
            "site has %s: the old and new state are BOTH resident across "
            "the step — donate argument 0 or annotate the line with "
            "'# edl: donate-ok(<why>)'" % (mod.dotted, name, param, what),
            "%s:%s" % (name, param),
        ))
    return findings


@register_pass(
    "donation",
    "step-shaped jitted functions (state-like first arg) must donate "
    "their state or carry an explicit donate-ok waiver",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.tree is None or "jit" not in mod.text:
            continue
        findings.extend(run_on_module(mod))
    return findings
