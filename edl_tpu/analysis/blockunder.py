"""Blocking-under-lock pass: nothing slow while a lock is held.

The recurring bug family this mechanizes: PR 9 found ``_mu`` held
across a 5-10 s store dial in the cache warmer and the AOT ladder;
PR 12's emergency ``flush()`` initially blocked behind a slow durable
mirror while holding the replicator pass lock. A lock held across a
blocking primitive turns one slow peer into a stall for every thread
that needs the lock — including supervision loops and RPC handlers.

Interprocedural: the held-lock sets come from graph.LockFlow, so a
locked method calling a helper that dials still fires (the helper is
walked with the caller's held set). The blocking catalogue is the
blocking-call pass's (hashing, subprocess, dials, ``urlopen``, long or
non-literal sleeps) extended with unbounded synchronization waits —
``.join()`` / ``.wait()`` / ``.wait_for()`` without a timeout.

Waivers at the offending call line: ``# edl: blocking-ok(<why>)`` or
``# edl: lock-free(<why>)``. A ``def``-level ``blocking-ok`` exempts
the function and stops traversal into it (it owns its latency budget).
``cv.wait()`` on a *held* Condition is exempt by construction — the
wait releases that lock — unless another lock is also held.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from edl_tpu.analysis.blocking import classify_blocking
from edl_tpu.analysis.core import AnalysisContext, Finding, register_pass
from edl_tpu.analysis.graph import lock_flow, lock_qualname


@register_pass(
    "blocking-under-lock",
    "no blocking primitive (dial/hash/subprocess/urlopen/long sleep/"
    "unbounded join or wait) reachable while a threading lock is held",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    flow = lock_flow(ctx)
    findings: List[Finding] = []
    occurrence: Dict[str, int] = {}
    seen_sites = set()  # one finding per offending site, first path wins
    for lc in flow.locked_calls:
        hit = classify_blocking(lc.call, include_sync=True)
        if hit is None:
            continue
        prim, what = hit
        info, call = lc.info, lc.call
        site = (info.mod.relpath, call.lineno, prim)
        if site in seen_sites:
            continue
        if (
            info.mod.annotation_on(call.lineno, "blocking-ok")
            or info.mod.annotation_on(call.lineno, "lock-free")
        ):
            continue
        if info.mod.annotation_for(info.node, "blocking-ok") is not None:
            continue
        held = list(lc.held)
        if prim == "wait.unbounded":
            # waiting on a condition you hold RELEASES it for the wait;
            # only other still-held locks make this a stall
            f = call.func
            if isinstance(f, ast.Attribute):
                recv = f.value
                if (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                ):
                    held = [
                        a for a in held if a.lid[2] != recv.attr
                        or a.lid[:2] != (info.fid[0], info.fid[1])
                    ]
                elif isinstance(recv, ast.Name):
                    held = [
                        a for a in held
                        if not (a.lid[1] is None and a.lid[2] == recv.id)
                    ]
            if not held:
                continue
        seen_sites.add(site)
        outer = held[0]
        ident_base = "%s:%s:%s" % (
            lc.chain[0], prim, lock_qualname(outer.lid).rsplit(".", 1)[-1]
        )
        n = occurrence.get(ident_base, 0)
        occurrence[ident_base] = n + 1
        root_kind = flow.root_for(lc.chain[0])
        via = (
            " [reached from a %s entry]" % root_kind if root_kind else ""
        )
        findings.append(Finding(
            "blocking-under-lock", info.mod.relpath, call.lineno, "error",
            "%s while holding %s (acquired at %s:%d; call path %s)%s — "
            "move the blocking work outside the lock or annotate the "
            "line with '# edl: blocking-ok(<why>)'" % (
                what, ", ".join(lock_qualname(a.lid) for a in held),
                outer.rel, outer.line, " -> ".join(lc.chain), via,
            ),
            ident_base if n == 0 else "%s#%d" % (ident_base, n),
        ))
    return findings
