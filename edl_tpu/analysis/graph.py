"""A conservative repo-wide symbol table and call-graph walker.

Name-based, flow-free, and deliberately modest: it resolves the call
shapes this codebase actually uses —

- ``foo()``              -> module-level def in the same module, or an
                            ``edl_tpu`` function/class imported by name
- ``self.meth()``        -> method of the enclosing class
- ``mod.foo()``          -> def in an imported ``edl_tpu`` module
- ``self.attr.meth()``   -> method of the class ``self.attr`` was
                            assigned from (``self.attr = Ctor(...)``)
- ``Ctor()``             -> that class's ``__init__``

Anything else stays unresolved; the blocking-call pass walks only what
resolves, so it under-approximates reachability rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import AnalysisContext, ModuleSource

# a function is identified by (module relpath, class name or None, name)
FuncId = Tuple[str, Optional[str], str]


class FuncInfo:
    def __init__(self, fid: FuncId, mod: ModuleSource, node: ast.AST) -> None:
        self.fid = fid
        self.mod = mod
        self.node = node

    @property
    def qualname(self) -> str:
        rel, cls, name = self.fid
        return "%s.%s" % (rel[:-3].replace("/", "."),
                          name if cls is None else "%s.%s" % (cls, name))


class SymbolTable:
    def __init__(self, ctx: AnalysisContext) -> None:
        self.ctx = ctx
        self.functions: Dict[FuncId, FuncInfo] = {}
        self.classes: Dict[Tuple[str, str], ast.ClassDef] = {}
        # module alias map per file: local name -> module relpath
        self.mod_imports: Dict[str, Dict[str, str]] = {}
        # imported symbol map per file: local name -> (relpath, symbol)
        self.sym_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # per class: attr name -> (relpath, class) from self.attr = Ctor()
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self._dotted_to_rel = {
            m.dotted: m.relpath for m in ctx.modules
        }
        for mod in ctx.modules:
            if mod.tree is not None:
                self._index_module(mod)
        for mod in ctx.modules:
            if mod.tree is not None:
                self._index_attr_types(mod)

    # -- indexing ----------------------------------------------------------

    def _rel_for_dotted(self, dotted: str) -> Optional[str]:
        if dotted in self._dotted_to_rel:
            return self._dotted_to_rel[dotted]
        return self._dotted_to_rel.get(dotted + ".__init__")

    def _index_module(self, mod: ModuleSource) -> None:
        rel = mod.relpath
        self.mod_imports[rel] = {}
        self.sym_imports[rel] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._rel_for_dotted(alias.name)
                    if target:
                        self.mod_imports[rel][
                            alias.asname or alias.name.split(".")[0]
                        ] = target
            elif isinstance(node, ast.ImportFrom) and node.module:
                src = self._rel_for_dotted(node.module)
                for alias in node.names:
                    sub = self._rel_for_dotted(
                        "%s.%s" % (node.module, alias.name)
                    )
                    local = alias.asname or alias.name
                    if sub:  # "from edl_tpu.store import client"
                        self.mod_imports[rel][local] = sub
                    elif src:  # "from edl_tpu.store.client import StoreClient"
                        self.sym_imports[rel][local] = (src, alias.name)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fid = (rel, None, node.name)
                self.functions[fid] = FuncInfo(fid, mod, node)
            elif isinstance(node, ast.ClassDef):
                self.classes[(rel, node.name)] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fid = (rel, node.name, sub.name)
                        self.functions[fid] = FuncInfo(fid, mod, sub)

    def resolve_symbol(
        self, rel: str, name: str
    ) -> Optional[Tuple[str, str]]:
        """Resolve a bare name in a module to (relpath, symbol)."""
        if (rel, None, name) in self.functions or (rel, name) in self.classes:
            return (rel, name)
        return self.sym_imports.get(rel, {}).get(name)

    def _index_attr_types(self, mod: ModuleSource) -> None:
        rel = mod.relpath
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            amap: Dict[str, Tuple[str, str]] = {}
            for stmt in ast.walk(node):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not (
                    isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                ):
                    continue
                target_cls = self.resolve_symbol(rel, stmt.value.func.id)
                if target_cls is None or target_cls not in self.classes:
                    continue
                for tgt in stmt.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        amap[tgt.attr] = target_cls
            if amap:
                self.attr_types[(rel, node.name)] = amap

    # -- call resolution ---------------------------------------------------

    def resolve_call(
        self, call: ast.Call, caller: FuncId
    ) -> Optional[FuncId]:
        rel, cls, _ = caller
        f = call.func
        if isinstance(f, ast.Name):
            sym = self.resolve_symbol(rel, f.id)
            if sym is None:
                return None
            srel, sname = sym
            if (srel, sname) in self.classes:  # constructor
                ctor = (srel, sname, "__init__")
                return ctor if ctor in self.functions else None
            fid = (srel, None, sname)
            return fid if fid in self.functions else None
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                fid = (rel, cls, f.attr)
                return fid if fid in self.functions else None
            if isinstance(base, ast.Name):
                target_mod = self.mod_imports.get(rel, {}).get(base.id)
                if target_mod:
                    fid = (target_mod, None, f.attr)
                    return fid if fid in self.functions else None
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls
            ):
                typ = self.attr_types.get((rel, cls), {}).get(base.attr)
                if typ:
                    fid = (typ[0], typ[1], f.attr)
                    return fid if fid in self.functions else None
        return None

    def calls_in(self, info: FuncInfo) -> List[Tuple[ast.Call, Optional[FuncId]]]:
        """Calls made *synchronously* by the function: nested defs and
        lambdas are skipped — a closure is typically handed to a side
        thread/executor and runs off the caller's loop, so charging its
        body to the caller would be a false positive (the cost: a
        closure invoked synchronously is under-reported)."""
        out = []
        stack: List[ast.AST] = list(
            info.node.body if isinstance(info.node.body, list)
            else [info.node.body]
        )
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                out.append((node, self.resolve_call(node, info.fid)))
            stack.extend(ast.iter_child_nodes(node))
        return out


def symbol_table(ctx: AnalysisContext) -> SymbolTable:
    table = ctx.cache.get("symbol_table")
    if table is None:
        table = SymbolTable(ctx)
        ctx.cache["symbol_table"] = table
    return table
