"""A conservative repo-wide symbol table and call-graph walker.

Name-based, flow-free, and deliberately modest: it resolves the call
shapes this codebase actually uses —

- ``foo()``              -> module-level def in the same module, or an
                            ``edl_tpu`` function/class imported by name
- ``self.meth()``        -> method of the enclosing class
- ``mod.foo()``          -> def in an imported ``edl_tpu`` module
- ``self.attr.meth()``   -> method of the class ``self.attr`` was
                            assigned from (``self.attr = Ctor(...)``)
- ``Ctor()``             -> that class's ``__init__``

Anything else stays unresolved; the blocking-call pass walks only what
resolves, so it under-approximates reachability rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import AnalysisContext, ModuleSource

# a function is identified by (module relpath, class name or None, name)
FuncId = Tuple[str, Optional[str], str]


class FuncInfo:
    def __init__(self, fid: FuncId, mod: ModuleSource, node: ast.AST) -> None:
        self.fid = fid
        self.mod = mod
        self.node = node

    @property
    def qualname(self) -> str:
        rel, cls, name = self.fid
        return "%s.%s" % (rel[:-3].replace("/", "."),
                          name if cls is None else "%s.%s" % (cls, name))


class SymbolTable:
    def __init__(self, ctx: AnalysisContext) -> None:
        self.ctx = ctx
        self.functions: Dict[FuncId, FuncInfo] = {}
        self.classes: Dict[Tuple[str, str], ast.ClassDef] = {}
        # module alias map per file: local name -> module relpath
        self.mod_imports: Dict[str, Dict[str, str]] = {}
        # imported symbol map per file: local name -> (relpath, symbol)
        self.sym_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # per class: attr name -> (relpath, class) from self.attr = Ctor()
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self._dotted_to_rel = {
            m.dotted: m.relpath for m in ctx.modules
        }
        for mod in ctx.modules:
            if mod.tree is not None:
                self._index_module(mod)
        for mod in ctx.modules:
            if mod.tree is not None:
                self._index_attr_types(mod)

    # -- indexing ----------------------------------------------------------

    def _rel_for_dotted(self, dotted: str) -> Optional[str]:
        if dotted in self._dotted_to_rel:
            return self._dotted_to_rel[dotted]
        return self._dotted_to_rel.get(dotted + ".__init__")

    def _index_module(self, mod: ModuleSource) -> None:
        rel = mod.relpath
        self.mod_imports[rel] = {}
        self.sym_imports[rel] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._rel_for_dotted(alias.name)
                    if target:
                        self.mod_imports[rel][
                            alias.asname or alias.name.split(".")[0]
                        ] = target
            elif isinstance(node, ast.ImportFrom) and node.module:
                src = self._rel_for_dotted(node.module)
                for alias in node.names:
                    sub = self._rel_for_dotted(
                        "%s.%s" % (node.module, alias.name)
                    )
                    local = alias.asname or alias.name
                    if sub:  # "from edl_tpu.store import client"
                        self.mod_imports[rel][local] = sub
                    elif src:  # "from edl_tpu.store.client import StoreClient"
                        self.sym_imports[rel][local] = (src, alias.name)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fid = (rel, None, node.name)
                self.functions[fid] = FuncInfo(fid, mod, node)
            elif isinstance(node, ast.ClassDef):
                self.classes[(rel, node.name)] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fid = (rel, node.name, sub.name)
                        self.functions[fid] = FuncInfo(fid, mod, sub)

    def resolve_symbol(
        self, rel: str, name: str
    ) -> Optional[Tuple[str, str]]:
        """Resolve a bare name in a module to (relpath, symbol)."""
        if (rel, None, name) in self.functions or (rel, name) in self.classes:
            return (rel, name)
        return self.sym_imports.get(rel, {}).get(name)

    def _index_attr_types(self, mod: ModuleSource) -> None:
        rel = mod.relpath
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            amap: Dict[str, Tuple[str, str]] = {}
            for stmt in ast.walk(node):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not (
                    isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                ):
                    continue
                target_cls = self.resolve_symbol(rel, stmt.value.func.id)
                if target_cls is None or target_cls not in self.classes:
                    continue
                for tgt in stmt.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        amap[tgt.attr] = target_cls
            if amap:
                self.attr_types[(rel, node.name)] = amap

    # -- call resolution ---------------------------------------------------

    def resolve_call(
        self, call: ast.Call, caller: FuncId
    ) -> Optional[FuncId]:
        rel, cls, _ = caller
        f = call.func
        if isinstance(f, ast.Name):
            sym = self.resolve_symbol(rel, f.id)
            if sym is None:
                return None
            srel, sname = sym
            if (srel, sname) in self.classes:  # constructor
                ctor = (srel, sname, "__init__")
                return ctor if ctor in self.functions else None
            fid = (srel, None, sname)
            return fid if fid in self.functions else None
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                fid = (rel, cls, f.attr)
                return fid if fid in self.functions else None
            if isinstance(base, ast.Name):
                target_mod = self.mod_imports.get(rel, {}).get(base.id)
                if target_mod:
                    fid = (target_mod, None, f.attr)
                    return fid if fid in self.functions else None
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls
            ):
                typ = self.attr_types.get((rel, cls), {}).get(base.attr)
                if typ:
                    fid = (typ[0], typ[1], f.attr)
                    return fid if fid in self.functions else None
        return None

    def calls_in(self, info: FuncInfo) -> List[Tuple[ast.Call, Optional[FuncId]]]:
        """Calls made *synchronously* by the function: nested defs and
        lambdas are skipped — a closure is typically handed to a side
        thread/executor and runs off the caller's loop, so charging its
        body to the caller would be a false positive (the cost: a
        closure invoked synchronously is under-reported)."""
        out = []
        stack: List[ast.AST] = list(
            info.node.body if isinstance(info.node.body, list)
            else [info.node.body]
        )
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                out.append((node, self.resolve_call(node, info.fid)))
            stack.extend(ast.iter_child_nodes(node))
        return out


def symbol_table(ctx: AnalysisContext) -> SymbolTable:
    table = ctx.cache.get("symbol_table")
    if table is None:
        table = SymbolTable(ctx)
        ctx.cache["symbol_table"] = table
    return table


# -- interprocedural lock-set propagation -------------------------------------
#
# The PR-9 pass generation was per-function: a lock held across a call
# was invisible the moment the call crossed a def boundary, which is
# exactly where the repo's worst lock bugs lived (a locked method
# calling a helper that dials, PR 9; an emergency flush blocking behind
# a slow mirror while holding the pass lock, PR 12). LockFlow walks
# every function with a syntactic held-lock stack and FOLLOWS resolved
# calls whenever the stack is non-empty, producing:
#
# - the global lock-acquisition-order graph (edges held-lock -> newly
#   acquired lock, each with a first-witness site and call path), fed
#   to the ``lock-order`` cycle/inversion pass;
# - every call site reached with a non-empty held set, fed to the
#   ``blocking-under-lock`` pass for blocking-primitive classification.
#
# Lock identity is declaration-based — ``(relpath, class, attr)`` for
# ``self._mu = threading.Lock()`` attrs, ``(relpath, None, name)`` for
# module-level locks — so two classes' ``_mu`` never alias. The walk is
# seeded from EVERY function (a superset of the thread-entry roots:
# thread targets, ``# edl: event-loop`` roots, and RPC handlers, which
# are still collected for reporting), so a lock taken in a public API
# method is tracked even when no in-tree thread reaches it.

LockId = Tuple[str, Optional[str], str]

_LOCK_CTOR_NAMES = ("Lock", "RLock", "Condition")
_LOCKFLOW_MAX_DEPTH = 12


class LockDecl:
    __slots__ = ("lid", "kind", "line")

    def __init__(self, lid: LockId, kind: str, line: int) -> None:
        self.lid = lid
        self.kind = kind  # "Lock" | "RLock" | "Condition"
        self.line = line


def lock_qualname(lid: LockId) -> str:
    rel, cls, name = lid
    mod = rel[:-3].replace("/", ".")
    return "%s.%s" % (mod, name if cls is None else "%s.%s" % (cls, name))


class _Acq:
    """One live acquisition on the walk stack: which lock, where."""

    __slots__ = ("lid", "rel", "line")

    def __init__(self, lid: LockId, rel: str, line: int) -> None:
        self.lid = lid
        self.rel = rel
        self.line = line


class OrderEdge:
    """First witness of ``held`` being held while ``acquired`` is
    taken: the acquisition site plus the call path from the entry
    function whose walk observed it."""

    __slots__ = ("held", "acquired", "rel", "line", "chain", "held_site")

    def __init__(self, held: _Acq, acquired: LockId, rel: str, line: int,
                 chain: Tuple[str, ...]) -> None:
        self.held = held.lid
        self.held_site = "%s:%d" % (held.rel, held.line)
        self.acquired = acquired
        self.rel = rel
        self.line = line
        self.chain = chain


class LockedCall:
    """A call expression reached while at least one lock is held."""

    __slots__ = ("info", "call", "held", "chain")

    def __init__(self, info: FuncInfo, call: ast.Call,
                 held: Tuple[_Acq, ...], chain: Tuple[str, ...]) -> None:
        self.info = info
        self.call = call
        self.held = held
        self.chain = chain


class LockFlow:
    def __init__(self, table: SymbolTable) -> None:
        self.table = table
        self.locks: Dict[LockId, LockDecl] = {}
        self.roots: Dict[FuncId, str] = {}  # fid -> root kind
        self.order_edges: Dict[Tuple[LockId, LockId], OrderEdge] = {}
        self.locked_calls: List[LockedCall] = []
        self._visited: set = set()
        self._regions: Dict[FuncId, List] = {}
        self._collect_locks()
        self._collect_roots()
        for info in table.functions.values():
            self._walk_fn(info, (), (info.qualname,))

    # -- declarations ------------------------------------------------------

    @staticmethod
    def _ctor_kind(value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        return name if name in _LOCK_CTOR_NAMES else None

    def _collect_locks(self) -> None:
        for info in self.table.functions.values():
            rel, cls, _ = info.fid
            if cls is None:
                continue
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Assign):
                    continue
                kind = self._ctor_kind(node.value)
                if kind is None:
                    continue
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        lid = (rel, cls, t.attr)
                        self.locks.setdefault(
                            lid, LockDecl(lid, kind, t.lineno)
                        )
        for mod in self.table.ctx.modules:
            if mod.tree is None:
                continue
            for node in mod.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                kind = self._ctor_kind(node.value)
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lid = (mod.relpath, None, t.id)
                        self.locks.setdefault(
                            lid, LockDecl(lid, kind, t.lineno)
                        )

    # -- thread-entry roots ------------------------------------------------

    def _collect_roots(self) -> None:
        """Thread targets, ``# edl: event-loop`` roots, and RPC handlers
        (``_op_*`` methods and ``_METHODS`` dispatch-table lambdas). The
        walk does not depend on these — every function is an entry — but
        findings report membership so a reader knows which concurrent
        context reaches the site."""
        for fid, info in self.table.functions.items():
            rel, cls, name = fid
            if info.mod.annotation_for(info.node, "event-loop") is not None:
                self.roots.setdefault(fid, "event-loop")
            if cls is not None and name.startswith("_op_"):
                self.roots.setdefault(fid, "rpc-handler")
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                ctor = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if ctor != "Thread":
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and cls is not None
                    ):
                        tfid = (rel, cls, tgt.attr)
                        if tfid in self.table.functions:
                            self.roots.setdefault(tfid, "thread-target")
                    elif isinstance(tgt, ast.Name):
                        sym = self.table.resolve_symbol(rel, tgt.id)
                        if sym is not None:
                            tfid = (sym[0], None, sym[1])
                            if tfid in self.table.functions:
                                self.roots.setdefault(tfid, "thread-target")
        # dispatch-table lambdas: _METHODS = {"op": lambda self, req:
        # self.handler(...)} — the bound handlers are RPC entry points
        for (rel, cls), node in self.table.classes.items():
            for stmt in node.body:
                if not (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Dict)
                    and any(
                        isinstance(t, ast.Name) and t.id == "_METHODS"
                        for t in stmt.targets
                    )
                ):
                    continue
                for value in stmt.value.values:
                    if not isinstance(value, ast.Lambda):
                        continue
                    for sub in ast.walk(value.body):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"
                        ):
                            tfid = (rel, cls, sub.func.attr)
                            if tfid in self.table.functions:
                                self.roots.setdefault(tfid, "rpc-handler")

    def root_for(self, chain_head: str) -> Optional[str]:
        for fid, kind in self.roots.items():
            if self.table.functions[fid].qualname == chain_head:
                return kind
        return None

    # -- the walk ----------------------------------------------------------

    def _lock_expr(self, info: FuncInfo, expr: ast.AST) -> Optional[LockId]:
        rel, cls, _ = info.fid
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            lid = (rel, cls, expr.attr)
            return lid if lid in self.locks else None
        if isinstance(expr, ast.Name):
            lid = (rel, None, expr.id)
            return lid if lid in self.locks else None
        return None

    def _acquire_regions(self, info: FuncInfo) -> List:
        """``lock.acquire()`` … ``lock.release()`` line intervals for
        explicit (non-``with``) holds — the PR-12 replicator pass-lock
        idiom (``acquire(timeout=...)`` + ``try/finally: release()``).
        Flow-insensitive: each acquire pairs with the next release of
        the same lock by line, or holds to the end of the function —
        the acquire-failed branch is over-approximated as held, which
        can only over-report."""
        regions = self._regions.get(info.fid)
        if regions is not None:
            return regions
        acquires: List[Tuple[LockId, int]] = []
        releases: Dict[LockId, List[int]] = {}
        for node in ast.walk(info.node):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("acquire", "release")
            ):
                continue
            lid = self._lock_expr(info, node.func.value)
            if lid is None:
                continue
            if node.func.attr == "acquire":
                acquires.append((lid, node.lineno))
            else:
                releases.setdefault(lid, []).append(node.lineno)
        regions = []
        fn_end = getattr(info.node, "end_lineno", None) or 10 ** 9
        for lid, line in acquires:
            later = [l for l in releases.get(lid, []) if l >= line]
            end = min(later) if later else fn_end
            regions.append((_Acq(lid, info.mod.relpath, line), line, end))
        self._regions[info.fid] = regions
        return regions

    def _effective_held(self, info: FuncInfo, lineno: int,
                        held: Tuple[_Acq, ...]) -> Tuple[_Acq, ...]:
        regions = self._acquire_regions(info)
        if not regions:
            return held
        out = list(held)
        for acq, start, end in regions:
            # strict > excludes the acquire call's own line
            if start < lineno <= end and all(
                h.lid != acq.lid for h in out
            ):
                out.append(acq)
        return tuple(out)

    def _walk_fn(self, info: FuncInfo, held: Tuple[_Acq, ...],
                 chain: Tuple[str, ...]) -> None:
        key = (info.fid, frozenset(a.lid for a in held))
        if key in self._visited or len(chain) > _LOCKFLOW_MAX_DEPTH:
            return
        self._visited.add(key)
        body = info.node.body if isinstance(info.node.body, list) else [
            info.node.body
        ]
        for stmt in body:
            self._walk_node(info, stmt, held, chain)

    def _walk_node(self, info: FuncInfo, node: ast.AST,
                   held: Tuple[_Acq, ...], chain: Tuple[str, ...]) -> None:
        # nested defs/lambdas run on their own schedule (typically a
        # side thread); same policy as SymbolTable.calls_in
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                expr = item.context_expr
                # the context expression itself evaluates BEFORE the
                # acquisition (e.g. ``with self._dial():``)
                self._walk_node(info, expr, new_held, chain)
                lid = self._lock_expr(info, expr)
                if lid is None:
                    continue
                line = expr.lineno
                eff = self._effective_held(info, line, new_held)
                waived = info.mod.annotation_on(
                    node.lineno, "lock-order-ok"
                ) or info.mod.annotation_on(line, "lock-order-ok")
                already = any(a.lid == lid for a in eff)
                if already and self.locks[lid].kind == "Lock" and not waived:
                    # re-entering a non-reentrant Lock: self-deadlock
                    self.order_edges.setdefault(
                        (lid, lid),
                        OrderEdge(_Acq(lid, info.mod.relpath, line), lid,
                                  info.mod.relpath, line, chain),
                    )
                if not waived:
                    for acq in eff:
                        if acq.lid == lid:
                            continue
                        self.order_edges.setdefault(
                            (acq.lid, lid),
                            OrderEdge(acq, lid, info.mod.relpath, line,
                                      chain),
                        )
                if not already:
                    new_held = new_held + (
                        _Acq(lid, info.mod.relpath, line),
                    )
            for stmt in node.body:
                self._walk_node(info, stmt, new_held, chain)
            return
        if isinstance(node, ast.Call):
            eff = self._effective_held(info, node.lineno, held)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                # explicit acquire while holding: an order edge (the
                # held region itself is tracked via _acquire_regions)
                lid = self._lock_expr(info, node.func.value)
                if lid is not None and not info.mod.annotation_on(
                    node.lineno, "lock-order-ok"
                ):
                    for acq in eff:
                        if acq.lid != lid:
                            self.order_edges.setdefault(
                                (acq.lid, lid),
                                OrderEdge(acq, lid, info.mod.relpath,
                                          node.lineno, chain),
                            )
            elif eff:
                self.locked_calls.append(LockedCall(info, node, eff, chain))
            callee = self.table.resolve_call(node, info.fid)
            if callee is not None and eff:
                sub = self.table.functions[callee]
                # a callee that owns its own latency budget is not
                # traversed (mirrors the blocking-call pass)
                if sub.mod.annotation_for(sub.node, "blocking-ok") is None:
                    self._walk_fn(sub, eff, chain + (sub.qualname,))
        for child in ast.iter_child_nodes(node):
            self._walk_node(info, child, held, chain)


def lock_flow(ctx: AnalysisContext) -> LockFlow:
    flow = ctx.cache.get("lock_flow")
    if flow is None:
        flow = LockFlow(symbol_table(ctx))
        ctx.cache["lock_flow"] = flow
    return flow
