"""Core of the edl-lint static-analysis plane.

The control plane is exactly the kind of code where bugs hide from
tests: watch loops, leader election, and process supervision are racy,
and the repo's history keeps paying for the same defect classes —
blocking work on a supervision loop (PR 8), torn writes (PR 3), and
unguarded cross-thread state (the still-open async-replication window).
This package turns those hand-fixed lessons into mechanical checks:

- every check is an :class:`AnalysisPass` over parsed
  :class:`ModuleSource` trees, registered in :data:`PASS_REGISTRY`;
- findings carry a *stable identity* (pass + path + symbol, never a
  line number) so a committed baseline survives unrelated edits;
- ``# edl: <verb>(<arg>)`` source annotations teach the analyzer
  (``guarded-by``, ``event-loop``) or record a deliberate exception
  (``lock-free``, ``blocking-ok``, ``durability-ok``, ``jit-ok``).

Drive it with ``python -m tools.edl_lint`` (see tools/edl_lint.py) or
in-process via :func:`run_analysis`.
"""

from __future__ import annotations

import ast
import dataclasses
import functools as _functools
import json
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning", "info")

# annotation grammar: "# edl: verb" or "# edl: verb(arg)" — verbs are
# kebab-case; the arg is free text up to the closing paren
ANNOTATION_RE = re.compile(
    r"#\s*edl:\s*([a-z][a-z-]*)\s*(?:\(([^)]*)\))?"
)

BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Annotation:
    verb: str
    arg: str
    line: int


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect report with a line for humans and a line-free
    identity for the baseline."""

    pass_name: str
    path: str          # repo-relative, forward slashes
    line: int
    severity: str
    message: str
    identity: str      # stable symbol-shaped id, e.g. "Monitor._pool"

    @property
    def key(self) -> str:
        return "%s:%s:%s" % (self.pass_name, self.path, self.identity)

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d

    def __str__(self) -> str:
        return "%s:%d: [%s] %s: %s" % (
            self.path, self.line, self.pass_name, self.severity, self.message
        )


class ModuleSource:
    """One parsed source file: text, AST, and ``# edl:`` annotations."""

    def __init__(self, root: Path, relpath: str) -> None:
        self.relpath = relpath
        self.abspath = Path(root, relpath)
        self.text = self.abspath.read_text()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=relpath)
        except SyntaxError as exc:
            self.parse_error = exc
        self.annotations: Dict[int, List[Annotation]] = {}
        for i, line in enumerate(self.lines, start=1):
            if "edl:" not in line:
                continue
            for m in ANNOTATION_RE.finditer(line):
                self.annotations.setdefault(i, []).append(
                    Annotation(m.group(1), (m.group(2) or "").strip(), i)
                )

    @property
    def dotted(self) -> str:
        """``edl_tpu/obs/trace.py`` -> ``edl_tpu.obs.trace``."""
        return self.relpath[:-3].replace("/", ".")

    def annotation_on(self, lineno: int, verb: str) -> Optional[Annotation]:
        """Annotation trailing the given line exactly. Use for statement
        -level annotations (assignments, calls): honoring the line above
        would leak a trailing annotation onto the next statement."""
        for ann in self.annotations.get(lineno, ()):
            if ann.verb == verb:
                return ann
        return None

    def annotation_at(self, lineno: int, verb: str) -> Optional[Annotation]:
        """Annotation on the given line or the line directly above it
        (for ``def`` lines, where a standalone comment above is idiom)."""
        for cand in (lineno, lineno - 1):
            for ann in self.annotations.get(cand, ()):
                if ann.verb == verb:
                    return ann
        return None

    def annotation_for(self, node: ast.AST, verb: str) -> Optional[Annotation]:
        """Annotation attached to a node: its first line, the line
        above, or (for decorated defs) above the first decorator."""
        ann = self.annotation_at(node.lineno, verb)
        if ann is not None:
            return ann
        decos = getattr(node, "decorator_list", None)
        if decos:
            return self.annotation_at(decos[0].lineno, verb)
        return None


class AnalysisContext:
    """Everything a pass may need: the parsed module set plus the
    DESIGN.md catalogue text (empty string when absent, so fixture
    trees in tests don't need one)."""

    def __init__(self, root: Path, modules: List[ModuleSource]) -> None:
        self.root = Path(root)
        self.modules = modules
        self.by_path = {m.relpath: m for m in modules}
        design = Path(root, "DESIGN.md")
        self.design_path = design
        self.design_text = design.read_text() if design.exists() else ""
        self.cache: Dict[str, object] = {}  # cross-pass memo (symbol tables)


@dataclasses.dataclass(frozen=True)
class AnalysisPass:
    name: str
    description: str
    run: Callable[[AnalysisContext], List[Finding]]


PASS_REGISTRY: Dict[str, AnalysisPass] = {}


def register_pass(name: str, description: str):
    def deco(fn: Callable[[AnalysisContext], List[Finding]]):
        if name in PASS_REGISTRY:
            raise ValueError("duplicate pass %r" % name)
        PASS_REGISTRY[name] = AnalysisPass(name, description, fn)
        return fn
    return deco


def discover_files(
    root: Path, subpaths: Sequence[str] = ("edl_tpu", "tools")
) -> List[str]:
    out: List[str] = []
    for sub in subpaths:
        base = Path(root, sub)
        if not base.exists():
            # a typo'd path silently analyzing nothing would read as
            # "clean"; fail loudly instead (CLI maps this to exit 2)
            raise FileNotFoundError(
                "no such path under %s: %s" % (root, sub)
            )
        if base.is_file() and base.suffix == ".py":
            out.append(str(base.relative_to(root)).replace("\\", "/"))
            continue
        for p in sorted(base.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            out.append(str(p.relative_to(root)).replace("\\", "/"))
    return out


def build_context(
    root, subpaths: Sequence[str] = ("edl_tpu", "tools")
) -> AnalysisContext:
    root = Path(root)
    modules = [ModuleSource(root, rel) for rel in discover_files(root, subpaths)]
    return AnalysisContext(root, modules)


@_functools.lru_cache(maxsize=1)
def repo_context() -> AnalysisContext:
    """The repo's own context, parsed once per process — the catalogue
    test wrappers (test_obs/test_chaos/test_monitor) and the analyzer's
    own tests all share it instead of re-parsing ~100 files each. The
    CLI builds fresh contexts and never uses this."""
    root = Path(__file__).resolve().parents[2]
    return build_context(root)


def run_analysis(
    ctx: AnalysisContext, only: Optional[Sequence[str]] = None
) -> Tuple[List[Finding], Dict[str, int]]:
    """Run (a subset of) the registered passes; returns findings sorted
    by (path, line) plus a per-pass finding count."""
    # passes register on import; pull them in lazily to avoid cycles
    from edl_tpu.analysis import (  # noqa: F401
        blocking, blockunder, catalogue, donation, durability, locks,
        lockorder, protocol, purity,
    )

    names = list(PASS_REGISTRY) if not only else list(only)
    unknown = [n for n in names if n not in PASS_REGISTRY]
    if unknown:
        raise KeyError("unknown pass(es): %s" % ", ".join(unknown))
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.parse_error is not None:
            findings.append(Finding(
                "parse", mod.relpath, mod.parse_error.lineno or 1, "error",
                "syntax error: %s" % mod.parse_error.msg, "syntax",
            ))
    counts: Dict[str, int] = {}
    for name in names:
        got = PASS_REGISTRY[name].run(ctx)
        counts[name] = len(got)
        findings.extend(got)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name, f.identity))
    return findings, counts


# -- baseline ----------------------------------------------------------------


def load_baseline(path) -> Dict[str, str]:
    """``{finding key: tracking note}``; missing file = empty baseline."""
    p = Path(path)
    if not p.exists():
        return {}
    doc = json.loads(p.read_text())
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            "baseline %s has version %r, want %d"
            % (path, doc.get("version"), BASELINE_VERSION)
        )
    return dict(doc.get("entries", {}))


def diff_baseline(
    findings: Iterable[Finding], baseline: Dict[str, str]
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split findings into (new, baselined); third element is the
    *stale* baseline keys — entries whose finding no longer occurs and
    should be expired with ``--write-baseline``."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen = set()
    for f in findings:
        seen.add(f.key)
        (old if f.key in baseline else new).append(f)
    stale = sorted(k for k in baseline if k not in seen)
    return new, old, stale


def write_baseline(
    path, findings: Iterable[Finding], notes: Optional[Dict[str, str]] = None,
    default_note: str = "baselined pre-existing finding; triage pending",
    keep: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Write the baseline for the given findings, carrying over any
    existing tracking notes; returns the entry map written. ``keep``
    holds entries to preserve verbatim — the CLI passes the entries of
    passes that did NOT run under ``--only``, so a partial run can't
    expire findings it never re-checked."""
    notes = notes or {}
    entries = dict(keep or {})
    for f in sorted(findings, key=lambda f: f.key):
        entries[f.key] = notes.get(f.key, default_note)
    doc = {"version": BASELINE_VERSION, "entries": entries}
    Path(path).write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return entries
