"""Lock-discipline pass.

The bug class: a class spins up a ``threading.Thread`` on one of its
own methods, and an instance attribute is then mutated both from that
thread and from caller-facing methods (``stop()``, ``poll()``, …)
without the owning lock — the monitor/exchange/store planes have all
paid for this. Two modes:

- **heuristic** (unannotated attrs): an attribute assigned both inside
  the thread-reachable method set and outside it (``__init__`` aside)
  must have every such assignment under a ``with self.<lock>:`` block;
  otherwise one warning per attribute.
- **declared** (``# edl: guarded-by(self._lock)`` on the attribute's
  ``__init__`` assignment): *every* access — load or store — outside
  ``__init__`` must hold that specific lock; violations are errors.

``# edl: lock-free(<why>)`` on the ``__init__`` assignment records a
deliberate lock-free design and suppresses the attribute entirely.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, ModuleSource, register_pass,
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


@dataclasses.dataclass(frozen=True)
class _Access:
    attr: str
    line: int
    locks: FrozenSet[str]   # "self._lock"-shaped names held at the site
    store: bool             # assignment vs read


class _MethodScan(ast.NodeVisitor):
    """Collect self-attribute accesses, held-lock context, intra-class
    calls, and thread targets for one method body."""

    def __init__(self) -> None:
        self.accesses: List[_Access] = []
        self.self_calls: Set[str] = set()
        self.thread_targets: Set[str] = set()
        self.lock_attrs: Dict[str, int] = {}  # attr -> assignment line
        self._held: List[str] = []

    # -- lock context ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                self._held.append("self.%s" % expr.attr)
                pushed += 1
            for sub in ast.walk(expr):
                if sub is not expr:
                    self.visit(sub)
        for stmt in node.body:
            self.visit(stmt)
        del self._held[len(self._held) - pushed:]

    # -- accesses ----------------------------------------------------------

    def _note(self, attr: str, line: int, store: bool) -> None:
        self.accesses.append(
            _Access(attr, line, frozenset(self._held), store)
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            self._note(node.attr, node.lineno,
                       isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    # (AugAssign targets need no special case: the target Attribute
    # carries Store ctx and visit_Attribute records it)

    # -- class facts -------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            self.self_calls.add(f.attr)
        ctor = None
        if isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS | {"Thread"}:
            ctor = f.attr
        elif isinstance(f, ast.Name) and f.id in _LOCK_CTORS | {"Thread"}:
            ctor = f.id
        if ctor == "Thread":
            for kw in node.keywords:
                if (
                    kw.arg == "target"
                    and isinstance(kw.value, ast.Attribute)
                    and isinstance(kw.value.value, ast.Name)
                    and kw.value.value.id == "self"
                ):
                    self.thread_targets.add(kw.value.attr)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            f = node.value.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name in _LOCK_CTORS:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.lock_attrs[t.attr] = t.lineno
        self.generic_visit(node)

    # nested defs (closures) run on unknown threads; their accesses are
    # deliberately still attributed to the enclosing method — a closure
    # handed to a Thread/executor from a reachable method is reachable
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _norm_lock(arg: str) -> str:
    arg = arg.strip()
    if arg.startswith("self."):
        return arg
    return "self." + arg


def _scan_class(
    mod: ModuleSource, cls: ast.ClassDef
) -> List[Finding]:
    scans: Dict[str, _MethodScan] = {}
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sc = _MethodScan()
            for sub in stmt.body:
                sc.visit(sub)
            scans[stmt.name] = sc

    locks: Set[str] = set()
    targets: Set[str] = set()
    for sc in scans.values():
        locks.update(sc.lock_attrs)
        targets.update(sc.thread_targets)

    # annotations live on the attribute's initialising assignment
    guarded: Dict[str, str] = {}
    lock_free: Set[str] = set()
    for name, sc in scans.items():
        for acc in sc.accesses:
            if not acc.store:
                continue
            ann = mod.annotation_on(acc.line, "guarded-by")
            if ann is not None and ann.arg:
                guarded[acc.attr] = _norm_lock(ann.arg)
            if mod.annotation_on(acc.line, "lock-free") is not None:
                lock_free.add(acc.attr)

    findings: List[Finding] = []

    # declared mode: every access outside __init__ under the named lock
    for attr, lock in sorted(guarded.items()):
        if attr in lock_free:
            continue
        bad: List[_Access] = []
        for name, sc in scans.items():
            if name == "__init__":
                continue
            for acc in sc.accesses:
                if acc.attr == attr and lock not in acc.locks:
                    if mod.annotation_on(acc.line, "lock-free") is not None:
                        continue
                    bad.append(acc)
        if bad:
            first = min(bad, key=lambda a: a.line)
            findings.append(Finding(
                "lock-discipline", mod.relpath, first.line, "error",
                "%s.%s is declared guarded-by(%s) but is accessed without "
                "it at %s" % (
                    cls.name, attr, lock,
                    ", ".join("line %d" % a.line for a in sorted(
                        bad, key=lambda a: a.line)[:6]),
                ),
                "%s.%s" % (cls.name, attr),
            ))

    if not targets:
        return findings

    reachable: Set[str] = set()
    frontier = [t for t in targets if t in scans]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        frontier.extend(
            c for c in scans[name].self_calls if c in scans and c not in reachable
        )

    # heuristic mode: attrs stored both inside and outside the
    # thread-reachable set, with at least one unlocked store
    stores: Dict[str, Dict[bool, List[_Access]]] = {}
    for name, sc in scans.items():
        if name == "__init__":
            continue
        in_thread = name in reachable
        for acc in sc.accesses:
            if not acc.store or acc.attr in locks:
                continue
            stores.setdefault(acc.attr, {True: [], False: []})[
                in_thread
            ].append(acc)

    for attr, sides in sorted(stores.items()):
        if attr in guarded or attr in lock_free or attr.startswith("__"):
            continue
        if not sides[True] or not sides[False]:
            continue
        unlocked = [
            a for a in sides[True] + sides[False]
            if not a.locks
            and mod.annotation_on(a.line, "lock-free") is None
        ]
        if not unlocked:
            continue
        first = min(unlocked, key=lambda a: a.line)
        findings.append(Finding(
            "lock-discipline", mod.relpath, first.line, "warning",
            "%s.%s is assigned from thread target(s) %s and from other "
            "methods, but not always under a lock (unlocked stores at %s); "
            "guard it, or annotate the __init__ assignment with "
            "'# edl: guarded-by(<lock>)' or '# edl: lock-free(<why>)'" % (
                cls.name, attr, "/".join(sorted(targets)),
                ", ".join("line %d" % a.line for a in sorted(
                    unlocked, key=lambda a: a.line)[:6]),
            ),
            "%s.%s" % (cls.name, attr),
        ))
    return findings


@register_pass(
    "lock-discipline",
    "instance attrs shared between a threading.Thread target and other "
    "methods must be mutated under the owning lock",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        if mod.tree is None:
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_scan_class(mod, node))
    return findings
