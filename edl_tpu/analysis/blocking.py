"""Blocking-call pass: nothing slow on a supervision/event loop.

The PR-8 bug class: the launcher supervision loop picked up a sha256
rescan over a multi-gigabyte compile-cache dir and every lease renewal
stalled behind it. The loops that must stay responsive are annotated at
the source with ``# edl: event-loop`` on the ``def`` line; this pass
walks the conservative call graph (see graph.py) from those roots and
flags blocking primitives anywhere in the reachable set:

- content hashing         (``hashlib.*``, ``*.file_digest``)
- process spawns          (``subprocess.run/Popen/check_*/call``)
- socket dials            (``socket.create_connection``, ``*.connect``)
- url fetches             (``urlopen``)
- long sleeps             (``time.sleep(literal >= 1.0)``) and
  unbounded sleeps        (``time.sleep(<non-literal>)``)

``# edl: blocking-ok(<why>)`` on the call line records a deliberate
exception (e.g. a bounded, deadline-guarded dial); on a ``def`` line it
exempts the whole function *and* stops traversal into it (the function
owns its own latency budget — typically a helper that hands work to a
side thread).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import AnalysisContext, Finding, register_pass
from edl_tpu.analysis.graph import FuncId, FuncInfo, symbol_table

_SLEEP_THRESHOLD_S = 1.0
_MAX_DEPTH = 10

_SUBPROCESS = {"run", "Popen", "call", "check_call", "check_output"}
_HASHLIB = {"sha256", "sha1", "sha512", "md5", "blake2b", "blake2s",
            "file_digest", "new"}


def _literal_float(node: ast.AST) -> Optional[float]:
    try:
        val = ast.literal_eval(node)
    except Exception:
        return None
    return float(val) if isinstance(val, (int, float)) else None


def _classify(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(primitive-id, message) for a blocking call, else None."""
    f = call.func
    head = None   # Name part: "hashlib" in hashlib.sha256
    attr = None
    if isinstance(f, ast.Attribute):
        attr = f.attr
        if isinstance(f.value, ast.Name):
            head = f.value.id
    elif isinstance(f, ast.Name):
        attr = f.id
    if head == "hashlib" and attr in _HASHLIB:
        return ("hashlib.%s" % attr, "content hashing (hashlib.%s)" % attr)
    if attr == "file_digest":
        return ("file_digest", "content hashing (file_digest)")
    if head == "subprocess" and attr in _SUBPROCESS:
        return ("subprocess.%s" % attr, "process spawn (subprocess.%s)" % attr)
    if attr == "create_connection":
        # head-independent: ``import socket as _socket`` must not hide
        # the dial (the name is specific enough to never false-match)
        return ("socket.create_connection", "socket dial (create_connection)")
    if attr == "connect" and isinstance(f, ast.Attribute):
        return ("connect", "socket dial (.connect)")
    if attr == "urlopen":
        return ("urlopen", "url fetch (urlopen)")
    if attr == "sleep" and head in (None, "time"):
        arg = call.args[0] if call.args else None
        if arg is None:
            return None
        lit = _literal_float(arg)
        if lit is None:
            return ("sleep.unbounded",
                    "sleep with a non-literal duration (unbounded?)")
        if lit >= _SLEEP_THRESHOLD_S:
            return ("sleep.long", "long sleep (%.3gs literal)" % lit)
    return None


def _has_timeout(call: ast.Call) -> bool:
    """A positional arg or a ``timeout=`` keyword bounds the wait."""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def classify_blocking(
    call: ast.Call, include_sync: bool = False
) -> Optional[Tuple[str, str]]:
    """Shared blocking-primitive catalogue. ``include_sync`` extends it
    with unbounded synchronization waits — ``x.join()`` and ``x.wait()``
    with no timeout — used by the blocking-under-lock pass (waiting
    forever is survivable on a plain thread, but not while holding a
    lock every other thread needs). ``"".join(parts)`` and
    ``done.wait(timeout)`` have arguments and never match."""
    hit = _classify(call)
    if hit is not None or not include_sync:
        return hit
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "join" and not call.args and not call.keywords:
        return ("join.unbounded", "thread join with no timeout")
    if f.attr == "wait" and not _has_timeout(call):
        return ("wait.unbounded", "wait() with no timeout")
    if f.attr == "wait_for" and len(call.args) < 2 and not any(
        kw.arg == "timeout" for kw in call.keywords
    ):
        return ("wait.unbounded", "wait_for() with no timeout")
    return None


@register_pass(
    "blocking-call",
    "no hashing/spawns/dials/long sleeps reachable from a function "
    "annotated '# edl: event-loop'",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    table = symbol_table(ctx)
    roots: List[FuncInfo] = []
    for info in table.functions.values():
        if info.mod.annotation_for(info.node, "event-loop") is not None:
            roots.append(info)

    findings: List[Finding] = []
    for root in roots:
        visited: Dict[FuncId, int] = {}
        # (callee, chain of qualnames from the root, depth)
        frontier: List[Tuple[FuncInfo, Tuple[str, ...]]] = [
            (root, (root.qualname,))
        ]
        occurrence: Dict[str, int] = {}
        while frontier:
            info, chain = frontier.pop(0)
            if info.fid in visited or len(chain) > _MAX_DEPTH:
                continue
            visited[info.fid] = len(chain)
            if (
                info is not root
                and info.mod.annotation_for(info.node, "blocking-ok")
                is not None
            ):
                continue
            for call, callee in table.calls_in(info):
                hit = _classify(call)
                if hit is not None:
                    prim, what = hit
                    if info.mod.annotation_on(call.lineno, "blocking-ok"):
                        continue
                    ident_base = "%s->%s:%s" % (
                        root.qualname, info.qualname, prim
                    )
                    n = occurrence.get(ident_base, 0)
                    occurrence[ident_base] = n + 1
                    findings.append(Finding(
                        "blocking-call", info.mod.relpath, call.lineno,
                        "error",
                        "%s on the '%s' event loop: %s (call path: %s); "
                        "move it off the loop or annotate the line with "
                        "'# edl: blocking-ok(<why>)'" % (
                            what, root.qualname, info.qualname,
                            " -> ".join(chain),
                        ),
                        ident_base if n == 0 else "%s#%d" % (ident_base, n),
                    ))
                if callee is not None and callee not in visited:
                    sub = table.functions[callee]
                    frontier.append((sub, chain + (sub.qualname,)))
    return findings
