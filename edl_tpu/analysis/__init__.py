"""edl-lint: the repo-wide static-analysis plane.

AST passes that mechanically enforce the invariants this codebase keeps
re-fixing by hand — lock discipline around thread-shared state,
nothing blocking on supervision/event loops, tmp+fsync+rename for
durable writes, purity of jit-traced functions, and conformance of the
DESIGN.md catalogues (metrics, fault points, monitor rules, EDL_* env
knobs). See core.py for the framework, tools/edl_lint.py for the CLI,
and DESIGN.md "Static analysis plane" for the pass table and
annotation grammar.
"""

from edl_tpu.analysis.core import (  # noqa: F401
    ANNOTATION_RE,
    AnalysisContext,
    AnalysisPass,
    Annotation,
    Finding,
    ModuleSource,
    PASS_REGISTRY,
    build_context,
    diff_baseline,
    discover_files,
    load_baseline,
    register_pass,
    repo_context,
    run_analysis,
    write_baseline,
)
from edl_tpu.analysis.catalogue import (  # noqa: F401
    collect_env_reads,
    collect_fault_points,
    collect_metric_registrations,
    generate_knob_catalogue,
)
from edl_tpu.analysis.protocol import (  # noqa: F401
    collect_protocol,
    generate_wire_catalogue,
)

__all__ = [
    "ANNOTATION_RE", "AnalysisContext", "AnalysisPass", "Annotation",
    "Finding", "ModuleSource", "PASS_REGISTRY", "build_context",
    "diff_baseline", "discover_files", "load_baseline", "register_pass",
    "repo_context", "run_analysis", "write_baseline", "collect_env_reads",
    "collect_fault_points", "collect_metric_registrations",
    "generate_knob_catalogue", "collect_protocol", "generate_wire_catalogue",
]
