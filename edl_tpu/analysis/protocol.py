"""Wire-protocol conformance pass + generated protocol catalogue.

Every op this control plane speaks (`"m"`-keyed request frames) was
added by hand on both sides of the wire — store client/server, the
data dispatcher, distill predict, the AOT cache exchange, checkpoint
peer replication, the replication stream — and the last four PRs each
hand-checked parity (``wb`` watch batches, ``repl_ack``,
``lease_renew_batch``, ``ckpt_push``/``ckpt_fetch``, the ``tc`` trace
field). This pass mechanizes the check:

- **op extraction, both ways**: every op literal clients send
  (``x.request("op")``, ``x._call("op")``, ``{"m": "op"}`` payload
  literals) is cross-checked against every op servers dispatch
  (``_op_<name>`` methods, ``_METHODS`` table keys, and
  ``req.get("m") == "op"`` comparisons). A sent op with no handler is
  an error; a handled op nothing in-tree sends is a warning (the
  native C++ twin may be the only caller — waive it at the handler).
- **frame parity**: server-initiated push frames (dict payloads with
  no ``"i"``/``"m"``/``"ok"`` key that flow into a send/pack call —
  the ``w``/``wb`` watch pushes, the replication stream's ``rl``
  batches) must have an in-tree decoder for their discriminator
  (first) key. Frames that ride a handler's *response* (the
  ``repl_sync`` ``snap`` bootstrap) are request/response payloads,
  not pushes, and are out of scope here.
- **tolerant optional decode**: client-injected optional fields
  (``tc``, ``tb``, ``e``) must be read with ``.get``; a ``["tc"]``
  subscript is a KeyError against any peer one PR older.
- **catalogue**: the table between the ``edl-lint:wire-catalogue``
  markers in DESIGN.md is generated (``--write-protocol-catalogue``);
  an op without a row, a row without an op, and any drift all fail.

``# edl: protocol-ok(<why>)`` on the send/handler/decode line waives a
site. Cross-file conclusions (unhandled/unsent/frames/catalogue) only
run when the context covers the full default scope — a path-narrowed
run has not seen both sides of the wire.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from edl_tpu.analysis.core import (
    AnalysisContext, Finding, ModuleSource, register_pass,
)

WIRE_BEGIN = "<!-- edl-lint:wire-catalogue:begin -->"
WIRE_END = "<!-- edl-lint:wire-catalogue:end -->"

# client-injected optional fields: every server decode must tolerate
# absence (an older peer never sends them). "rev" (MVCC pin), "rm"
# (standby-read opt-in) and "minr" (session floor) joined with the
# released-revision read plane — the native twin and any one-PR-older
# peer omit all three. "dl" (predict deadline), "qd"/"ew" (admission
# queue depth / est-wait echo) joined with the serving resilience
# plane under the same compatibility contract.
OPTIONAL_FIELDS = ("tc", "tb", "e", "rev", "rm", "minr", "dl", "qd", "ew")

# response/request bookkeeping keys that mark a dict literal as NOT a
# push frame
_RPC_KEYS = {"i", "m", "ok"}

_SENDISH = {
    "_send", "send", "sendall", "pack_frame", "pack_frame_buffers",
    "send_buffers", "request_once",
}

Site = Tuple[str, int]  # (relpath, line)


class ProtocolFacts:
    def __init__(self) -> None:
        self.sent: Dict[str, List[Site]] = {}
        self.handled: Dict[str, List[Site]] = {}
        self.frames_sent: Dict[str, List[Site]] = {}
        self.frames_decoded: Dict[str, List[Site]] = {}
        # (rel, line, field, scope-qualname)
        self.intolerant: List[Tuple[str, int, str, str]] = []
        self.modules: set = set()  # relpaths with any send/handle site

    def _note(self, table: Dict[str, List[Site]], key: str,
              rel: str, line: int) -> None:
        table.setdefault(key, []).append((rel, line))


def _call_name(f: ast.AST) -> Optional[str]:
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_get_m(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
        and isinstance(node.args[0], ast.Constant)
        and node.args[0].value == "m"
    )


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scan_function(facts: ProtocolFacts, mod: ModuleSource,
                   fn: ast.AST) -> None:
    rel = mod.relpath
    method_vars: set = set()      # names assigned from <x>.get("m")
    dict_assigns: Dict[str, ast.Dict] = {}
    sent_names: set = set()       # names passed to send-ish calls

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if _is_get_m(node.value):
                    method_vars.add(tgt.id)
                elif isinstance(node.value, ast.Dict):
                    dict_assigns[tgt.id] = node.value

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _call_name(node.func)
            if (
                name in ("request", "_call")
                and node.args
                and isinstance(node.func, ast.Attribute)
            ):
                op = _const_str(node.args[0])
                if op is not None:
                    facts._note(facts.sent, op, rel, node.lineno)
            if name in _SENDISH:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        sent_names.add(arg.id)
                    elif isinstance(arg, ast.Dict):
                        _note_frame(facts, mod, arg)
        elif isinstance(node, ast.Dict):
            # zip keys/values directly: a ``**base`` unpacking entry is a
            # None key, and filtering it first would misalign the index
            for k, v in zip(node.keys, node.values):
                if k is not None and _const_str(k) == "m":
                    op = _const_str(v)
                    if op is not None:
                        facts._note(facts.sent, op, rel, node.lineno)
                    break
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            op_node = node.ops[0]
            if isinstance(op_node, (ast.Eq, ast.NotEq)):
                sides = (node.left, node.comparators[0])
                for a, b in (sides, sides[::-1]):
                    lit = _const_str(a)
                    if lit is None:
                        continue
                    if _is_get_m(b) or (
                        isinstance(b, ast.Name) and b.id in method_vars
                    ):
                        facts._note(facts.handled, lit, rel, node.lineno)

    for name in sent_names & set(dict_assigns):
        _note_frame(facts, mod, dict_assigns[name])


def _note_frame(facts: ProtocolFacts, mod: ModuleSource,
                node: ast.Dict) -> None:
    keys = [_const_str(k) for k in node.keys if k is not None]
    if not keys or any(k is None for k in keys):
        return
    if _RPC_KEYS & set(keys):
        return
    facts._note(facts.frames_sent, keys[0], mod.relpath, node.lineno)


def collect_protocol(ctx: AnalysisContext) -> ProtocolFacts:
    facts = ctx.cache.get("protocol_facts")
    if facts is None:
        facts = _collect_protocol(ctx)
        ctx.cache["protocol_facts"] = facts
    return facts


def _collect_protocol(ctx: AnalysisContext) -> ProtocolFacts:
    from edl_tpu.analysis.graph import symbol_table

    facts = ProtocolFacts()
    table = symbol_table(ctx)
    for info in table.functions.values():
        _scan_function(facts, info.mod, info.node)
    for mod in ctx.modules:
        if mod.tree is None:
            continue
        # _op_* dispatch methods and _METHODS dispatch tables
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name.startswith("_op_")
                ):
                    facts._note(
                        facts.handled, stmt.name[4:], mod.relpath,
                        stmt.lineno,
                    )
                elif (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Dict)
                    and any(
                        isinstance(t, ast.Name) and t.id == "_METHODS"
                        for t in stmt.targets
                    )
                ):
                    for k in stmt.value.keys:
                        op = _const_str(k) if k is not None else None
                        if op is not None:
                            facts._note(
                                facts.handled, op, mod.relpath, k.lineno
                            )
    for op, sites in list(facts.sent.items()) + list(facts.handled.items()):
        for rel, _ in sites:
            facts.modules.add(rel)

    # decode sites for pushed frame discriminators + tolerant-decode
    # audit of the optional fields, scoped to protocol modules (a
    # `"w" in mode` string test in an unrelated module must not count
    # as decoding the watch-push frame)
    frame_keys = set(facts.frames_sent)
    for rel in facts.frames_sent.values():
        for r, _ in rel:
            facts.modules.add(r)
    for mod in ctx.modules:
        if mod.tree is None:
            continue
        if mod.relpath not in facts.modules:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Compare) and any(
                isinstance(o, (ast.In, ast.NotIn)) for o in node.ops
            ):
                lit = _const_str(node.left)
                if lit in frame_keys:
                    facts._note(
                        facts.frames_decoded, lit, mod.relpath, node.lineno
                    )
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute) and f.attr == "get"
                    and node.args
                ):
                    lit = _const_str(node.args[0])
                    if lit in frame_keys:
                        facts._note(
                            facts.frames_decoded, lit, mod.relpath,
                            node.lineno,
                        )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                lit = _const_str(node.slice)
                if lit in frame_keys:
                    facts._note(
                        facts.frames_decoded, lit, mod.relpath, node.lineno
                    )
                if lit in OPTIONAL_FIELDS:
                    facts.intolerant.append(
                        (mod.relpath, node.lineno, lit, mod.dotted)
                    )
    return facts


# -- catalogue ----------------------------------------------------------------


def _mods(sites: List[Site]) -> str:
    mods = sorted({rel[:-3].replace("/", ".") for rel, _ in sites})
    return ", ".join(mods[:4]) + (
        ", … +%d" % (len(mods) - 4) if len(mods) > 4 else ""
    )


def generate_wire_catalogue(ctx: AnalysisContext) -> str:
    facts = collect_protocol(ctx)
    lines = [
        WIRE_BEGIN,
        "<!-- generated by `python -m tools.edl_lint "
        "--write-protocol-catalogue`; do not hand-edit rows -->",
        "",
        "| op | kind | sent by | handled by |",
        "|---|---|---|---|",
    ]
    for op in sorted(set(facts.sent) | set(facts.handled)):
        lines.append("| `%s` | rpc | %s | %s |" % (
            op,
            _mods(facts.sent.get(op, [])) or "—",
            _mods(facts.handled.get(op, [])) or "—",
        ))
    for key in sorted(facts.frames_sent):
        lines.append("| `%s` | frame | %s | %s |" % (
            key,
            _mods(facts.frames_sent[key]),
            _mods(facts.frames_decoded.get(key, [])) or "—",
        ))
    lines.append("")
    lines.append(WIRE_END)
    return "\n".join(lines)


def extract_wire_block(design_text: str) -> Optional[str]:
    begin = design_text.find(WIRE_BEGIN)
    end = design_text.find(WIRE_END)
    if begin < 0 or end < 0 or end < begin:
        return None
    return design_text[begin:end + len(WIRE_END)]


def catalogued_ops(design_text: str) -> Dict[str, str]:
    """op/frame name -> kind column, parsed from the marker block."""
    block = extract_wire_block(design_text)
    if block is None:
        return {}
    out = {}
    for m in re.finditer(
        r"^\|\s*`([a-z0-9_]+)`\s*\|\s*(rpc|frame)\s*\|", block, re.MULTILINE
    ):
        out[m.group(1)] = m.group(2)
    return out


# -- the pass -----------------------------------------------------------------


def _unwaived(ctx: AnalysisContext, sites: List[Site]) -> List[Site]:
    out = []
    for rel, line in sites:
        mod = ctx.by_path.get(rel)
        if mod is not None and mod.annotation_on(line, "protocol-ok"):
            continue
        out.append((rel, line))
    return out


@register_pass(
    "wire-protocol",
    "client-sent ops, server dispatch tables, push-frame decoders and "
    "the DESIGN.md wire catalogue must agree both ways",
)
def run(ctx: AnalysisContext) -> List[Finding]:
    from edl_tpu.analysis.catalogue import _covers_default_scope

    facts = collect_protocol(ctx)
    findings: List[Finding] = []

    for rel, line, field, scope in facts.intolerant:
        mod = ctx.by_path.get(rel)
        if mod is not None and mod.annotation_on(line, "protocol-ok"):
            continue
        findings.append(Finding(
            "wire-protocol", rel, line, "error",
            "optional wire field %r read with a [] subscript — a peer "
            "that predates the field never sends it, so this is a "
            "KeyError mid-protocol; use .get(%r)" % (field, field),
            "intolerant:%s:%s" % (field, scope),
        ))

    if not _covers_default_scope(ctx):
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    for op in sorted(facts.sent):
        if op in facts.handled:
            continue
        sites = _unwaived(ctx, facts.sent[op])
        if not sites:
            continue
        rel, line = sites[0]
        findings.append(Finding(
            "wire-protocol", rel, line, "error",
            "clients send op %r (%d site%s) but no server dispatch "
            "handles it — every request will fail with 'unknown "
            "method'; add the handler or waive the send with "
            "'# edl: protocol-ok(<why>)'" % (
                op, len(sites), "" if len(sites) == 1 else "s",
            ),
            "unhandled:%s" % op,
        ))
    for op in sorted(facts.handled):
        if op in facts.sent:
            continue
        sites = _unwaived(ctx, facts.handled[op])
        if not sites:
            continue
        rel, line = sites[0]
        findings.append(Finding(
            "wire-protocol", rel, line, "warning",
            "server handles op %r but nothing in-tree sends it — dead "
            "dispatch, or a native-twin-only op; delete it or waive "
            "the handler with '# edl: protocol-ok(<why>)'" % op,
            "unsent:%s" % op,
        ))
    for key in sorted(facts.frames_sent):
        if key in facts.frames_decoded:
            continue
        sites = _unwaived(ctx, facts.frames_sent[key])
        if not sites:
            continue
        rel, line = sites[0]
        findings.append(Finding(
            "wire-protocol", rel, line, "error",
            "server push frame %r has no in-tree decoder (no peer "
            "tests/gets/indexes the key) — receivers will drop or "
            "choke on it; add the decode or waive the send with "
            "'# edl: protocol-ok(<why>)'" % key,
            "frame-undecoded:%s" % key,
        ))

    # catalogue conformance (generated table in DESIGN.md)
    if ctx.design_text:
        block = extract_wire_block(ctx.design_text)
        if block is None:
            findings.append(Finding(
                "wire-protocol", "DESIGN.md", 1, "error",
                "DESIGN.md has no wire-catalogue markers (%s … %s); run "
                "python -m tools.edl_lint --write-protocol-catalogue"
                % (WIRE_BEGIN, WIRE_END),
                "markers",
            ))
        else:
            rows = catalogued_ops(ctx.design_text)
            known = set(facts.sent) | set(facts.handled) | set(
                facts.frames_sent
            )
            for op in sorted(known - set(rows)):
                sites = (
                    facts.sent.get(op) or facts.handled.get(op)
                    or facts.frames_sent.get(op)
                )
                rel, line = sites[0]
                findings.append(Finding(
                    "wire-protocol", rel, line, "error",
                    "op `%s` has no row in the DESIGN.md wire-protocol "
                    "catalogue; run python -m tools.edl_lint "
                    "--write-protocol-catalogue" % op,
                    "uncatalogued:%s" % op,
                ))
            for op in sorted(set(rows) - known):
                findings.append(Finding(
                    "wire-protocol", "DESIGN.md", 1, "warning",
                    "the wire-protocol catalogue lists `%s` but no code "
                    "sends or handles it any more; regenerate with "
                    "--write-protocol-catalogue" % op,
                    "stale-row:%s" % op,
                ))
            if block.strip() != generate_wire_catalogue(ctx).strip():
                findings.append(Finding(
                    "wire-protocol", "DESIGN.md", 1, "error",
                    "the DESIGN.md wire-protocol catalogue has drifted "
                    "from the code; run python -m tools.edl_lint "
                    "--write-protocol-catalogue",
                    "drift",
                ))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
