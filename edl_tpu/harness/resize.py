"""Elastic resize harness: scheduled pod churn against a live job.

Capability parity with the reference's job-server/job-client demo pair
(SURVEY §2 C26: a ``job_server_demo`` emitting scale events every
``--time_interval_to_change`` seconds and per-node ``job_client_demo``
(re)starting pods, reference README.md:108-142) — plus what the reference
lacks (SURVEY §5: "fault injection: nothing purpose-built"): deterministic
schedules and SIGKILL fault injection, so elasticity is testable by
asserts, not wall-clock demos.

The harness owns a set of local launcher processes ("pods") for one job
and walks them through a resize schedule: at each step it grows by
starting fresh ``python -m edl_tpu.launch`` processes or shrinks by
killing (SIGKILL — a dead machine, not a clean exit) the youngest pods.
The launcher's drain/re-barrier state machine does the rest.

CLI::

    python -m edl_tpu.harness.resize --store HOST:PORT --job_id j1 \
        --schedule 2,4,2,8 --interval 60 -- train.py --epochs 90
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from edl_tpu.store.client import StoreClient, connect_store
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.log import get_logger

logger = get_logger("harness.resize")


class ResizeHarness:
    def __init__(
        self,
        store_endpoint: str,
        job_id: str,
        training_script: str,
        training_args: Sequence[str] = (),
        nodes_range: str = "1:8",
        nproc_per_node: int = 1,
        ttl: float = 10.0,
        log_dir: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
    ) -> None:
        self.store_endpoint = store_endpoint
        self.job_id = job_id
        self.training_script = training_script
        self.training_args = list(training_args)
        self.nodes_range = nodes_range
        self.nproc = nproc_per_node
        self.ttl = ttl
        self.log_dir = log_dir
        self.extra_env = dict(extra_env or {})
        self.pods: List[subprocess.Popen] = []
        self._client: Optional[StoreClient] = None
        self._peak_world = 0
        self._archived = False

    # -- pod management ----------------------------------------------------

    def start_pod(self) -> subprocess.Popen:
        env = dict(os.environ)
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(self.extra_env)
        cmd = [
            sys.executable, "-m", "edl_tpu.launch",
            "--job_id", self.job_id,
            "--store", self.store_endpoint,
            "--nodes_range", self.nodes_range,
            "--nproc_per_node", str(self.nproc),
            "--ttl", str(self.ttl),
        ]
        if self.log_dir:
            cmd += ["--log_dir", self.log_dir]
        cmd += [self.training_script, *self.training_args]
        proc = subprocess.Popen(cmd, env=env)
        self.pods.append(proc)
        self._peak_world = max(self._peak_world, len(self.pods))
        logger.info("started pod pid=%d (now %d)", proc.pid, len(self.pods))
        return proc

    def kill_pod(self, proc: subprocess.Popen, sig=signal.SIGKILL) -> None:
        """SIGKILL = machine death: the store lease must expire before the
        cluster converges — the failure mode the reference handles with its
        'sleep 15 > TTL 10' coupling (launch.py:228-230)."""
        try:
            proc.send_signal(sig)
        except ProcessLookupError:
            pass
        proc.wait()
        self.pods.remove(proc)
        logger.info("killed pod pid=%d (now %d)", proc.pid, len(self.pods))

    def resize_to(self, n: int) -> None:
        self._reap()
        while len(self.pods) < n:
            self.start_pod()
        while len(self.pods) > n:
            self.kill_pod(self.pods[-1])

    def restart_pod(self) -> None:
        """SIGKILL the youngest pod and immediately start a replacement:
        the same-world-size recovery drill (machine replaced, capacity
        unchanged). The survivors drain on the lease expiry and the
        replacement joins the new stage — downtime is drain to the new
        stage's first step, exactly a grow transition's path minus the
        world-size change."""
        self._reap()
        if self.pods:
            self.kill_pod(self.pods[-1])
        self.start_pod()

    def _reap(self) -> None:
        self.pods = [p for p in self.pods if p.poll() is None]

    # -- job observation ---------------------------------------------------

    def job_complete(self) -> bool:
        if self._client is None:
            self._client = connect_store(self.store_endpoint, timeout=5.0)
        try:
            # retrying: the poll must ride a store failover (the
            # store-failover drill kills the primary mid-schedule) the
            # same way the job's own clients do
            value = self._client.retrying(
                "get", retries=10, k="/%s/job/status" % self.job_id
            )["v"]
        except EdlStoreError:
            return False  # control plane mid-outage: poll again next tick
        return value == b"COMPLETE"

    def live_pod_count(self) -> int:
        self._reap()
        return len(self.pods)

    # -- the churn loop ----------------------------------------------------

    def run_schedule(
        self,
        schedule: Sequence,
        interval: float,
        timeout: float = 3600.0,
    ) -> bool:
        """Walk the pod count through ``schedule``, ``interval`` seconds per
        step, then hold the final size until the job completes. A ``"r"``
        entry restarts the youngest pod (kill -9 + replace) instead of
        resizing — the constant-capacity recovery drill. Returns True if
        the job completed."""
        deadline = time.time() + timeout
        for want in schedule:
            if self.job_complete() or time.time() > deadline:
                break
            if want == "r":
                logger.info("restart youngest pod")
                self.restart_pod()
            else:
                logger.info("resize -> %d pods", want)
                self.resize_to(want)
            step_end = time.time() + interval
            while time.time() < step_end:
                if self.job_complete() or time.time() > deadline:
                    break
                time.sleep(min(1.0, interval / 10))
        while not self.job_complete() and time.time() < deadline:
            self._reap()
            if not self.pods:  # everyone exited without COMPLETE: failure
                return self.job_complete()
            time.sleep(0.5)
        return self.job_complete()

    def shutdown(self) -> None:
        for proc in list(self.pods):
            self.kill_pod(proc, sig=signal.SIGTERM)
        self._maybe_archive()
        if self._client is not None:
            self._client.close()
            self._client = None

    def _maybe_archive(self) -> None:
        """Run-archive hook (``EDL_RUN_ARCHIVE``): the harness owns the
        whole run, so at shutdown — pods reaped, trace exports and
        flight segments final — it harvests them into one indexed
        bundle. Consulted against the env the pods actually saw
        (``extra_env`` over the process env); the chaos rig and the
        bench tools set ``EDL_RUN_ARCHIVE=0`` here because they archive
        richer bundles (invariant verdicts / bench rollups) themselves."""
        if self._archived:
            return
        from edl_tpu.obs import archive as run_archive

        env = dict(os.environ)
        env.update(self.extra_env)
        root = run_archive.archive_root(env=env)
        if not root:
            return
        self._archived = True
        try:
            run_archive.RunArchive(root).archive(
                "job",
                self.job_id,
                backend=run_archive.backend_guess(env),
                world=self._peak_world or None,
                flight_dir=env.get("EDL_FLIGHT_DIR"),
                trace_dir=env.get("EDL_TRACE_DIR"),
                monitor_dir=env.get("EDL_MONITOR_DIR"),
                chaos_log=env.get("EDL_CHAOS_LOG"),
                knobs=run_archive.knob_snapshot(self.extra_env),
            )
        except Exception as exc:  # noqa: BLE001 — archiving must not
            # turn a completed job into a failed one
            logger.warning("run archive failed: %s", exc)


def parse_schedule(text: str) -> list:
    """``"2,4,r,2"`` -> ``[2, 4, "r", 2]`` (shared by both CLIs)."""
    return [x if x == "r" else int(x) for x in text.split(",")]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.harness.resize",
        description="Scheduled elastic resize driver (≙ reference job server demo)",
    )
    parser.add_argument("--store", required=True)
    parser.add_argument("--job_id", default="resize-demo")
    parser.add_argument(
        "--schedule", default="2,4,2",
        help="comma pod counts; an 'r' entry kill -9s the youngest pod "
        "and replaces it (constant-capacity recovery drill)",
    )
    parser.add_argument("--interval", type=float, default=60.0)
    parser.add_argument("--nodes_range", default="1:8")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--ttl", type=float, default=10.0)
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("--timeout", type=float, default=3600.0)
    parser.add_argument("training_script")
    parser.add_argument("training_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    harness = ResizeHarness(
        args.store,
        args.job_id,
        args.training_script,
        args.training_args,
        nodes_range=args.nodes_range,
        nproc_per_node=args.nproc_per_node,
        ttl=args.ttl,
        log_dir=args.log_dir,
    )
    try:
        done = harness.run_schedule(
            parse_schedule(args.schedule),
            args.interval,
            timeout=args.timeout,
        )
        return 0 if done else 1
    finally:
        harness.shutdown()


if __name__ == "__main__":
    sys.exit(main())
