"""Store-layout and process-contract constants shared by the launcher and
the worker-side train context.

Both sides of the elastic handshake must agree on these, but the launcher
must not import the jax-heavy train package and workers must not import
the launcher — so the shared values live here, in the light cluster
package both already depend on.
"""

# services under the job root (see launch/launcher.py module docstring for
# the full layout)
RES_SERVICE = "pod_resource"
RANK_SERVICE = "pod_rank"
DRAIN_SERVICE = "drain"
CLUSTER_SERVICE = "cluster"
STATUS_SERVICE = "status"
JOB_SERVICE = "job"
# hot restage: worker {pod_id}.{rank_in_pod} -> stage it adopted in-process
HOTADOPT_SERVICE = "hotadopt"

# health plane (see launch/launcher.py for the full keyspace docs):
# preempt/{pod_id} -> json {"deadline": wall-ts, "budget": s, "ts": ...}
#   published by a launcher that received an advance preemption notice
#   (SIGTERM/SIGUSR1). The leader excludes noticed pods from the next
#   generation immediately — no lease-expiry wait — and the pod's own
#   workers see the key through a store watch, take an emergency
#   checkpoint within the budget, and exit DRAINED_EXIT.
PREEMPT_SERVICE = "preempt"
# heartbeat/{pod_id}.{rank_in_pod} -> json {"step": N, "ts": wall-ts,
#   "dt": last-step-seconds, "stage": stage} — per-step worker progress,
#   throttled to EDL_HEARTBEAT_EVERY seconds. The launcher-side straggler
#   watchdog compares each of ITS workers' heartbeat age against a
#   peer-median-derived deadline to tell "stalled" from "uniformly slow".
HEARTBEAT_SERVICE = "heartbeat"

# scale plane (see edl_tpu/scale/ and DESIGN.md "Scale plane"):
# scale/target -> json {"pods": N, "seq": K, "cause": ..., "ts": wall-ts}
#   the autoscaler's reconciliation target for THIS job's world size,
#   written by tools/edl_scaled.py (permanent, last-writer-wins). The
#   leader launcher caps its published world at max(pods, min_nodes)
#   (pods == 0 pauses the job: all pods drained, and the next leader
#   publishes the EMPTY generation so the pause is visible in
#   cluster/current rather than inferred from silence),
#   shrinking via preempt/{pod} notices with cause=autoscale and growing
#   by admitting held pods on the next membership convergence.
# scale/decision -> json rich last-decision record (kind/target/cause/
#   score/seq/trace) — observability only; edl-top's SCHEDULER panel.
SCALE_SERVICE = "scale"

# memory plane (service name owned by edl_tpu/obs/memory.py:MEM_SERVICE;
# see DESIGN.md "Memory observability plane"):
# mem/plan/{world} -> json compile-time MemoryPlan doc (per-kind bytes,
#   total, the publishing device's limit) for the train step compiled at
#   that world — written by the live stage and every AOT ladder rung
#   (permanent, last-writer-wins). The scaler and the launcher's
#   reconcile path read the whole service to fit-gate resize targets
#   (refusals carry cause mem_unfit; growth only is ever clamped).

# exit code a hot-restage-capable worker uses to say "I could not adopt
# the new stage in-process; respawn me" — the launcher treats it as a
# restage request, not a job failure (only in hot-restage mode)
HOT_RESTAGE_EXIT = 75

# exit code of a gracefully drained process: a worker exits with it after
# its emergency checkpoint, and the launcher itself returns it once the
# pod's drain completes — supervisors must treat it as a clean departure,
# never a crash (no failure grace window, no restart of this pod)
DRAINED_EXIT = 76

COMPLETE = b"COMPLETE"
