"""Job and worker environment contracts.

Capability parity with the reference's ``JobEnv``/``TrainerEnv``
(python/edl/utils/edl_env.py:30-180): job config merged from CLI args and
``EDL_*`` env vars, elastic node window "min:max", per-node process count,
checkpoint path — and the worker-side env the process manager injects
(reference edl_process.py:54-62 injects ``PADDLE_TRAINER_*``; we inject
``EDL_*`` consumed by :func:`edl_tpu.train.init` to drive
``jax.distributed.initialize``).

TPU topology: instead of ``get_cuda_device_count`` (reference
utils.py:98-120), the local device count comes from ``EDL_DEVICES_PER_PROC``
when set (CPU-simulated meshes in tests) else lazily from ``jax`` on first
use — control-plane processes that never ask never import jax.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from edl_tpu.utils.log import get_logger

logger = get_logger("cluster.job_env")

MAX_PODS = 1024  # reference caps the elastic window at 1024 nodes


def _parse_nodes_range(spec: str) -> Tuple[int, int]:
    """Parse "min:max" / "n" (fixed) elastic node windows."""
    if ":" in spec:
        lo_s, hi_s = spec.split(":", 1)
        lo, hi = int(lo_s), int(hi_s)
    else:
        lo = hi = int(spec)
    if not (1 <= lo <= hi <= MAX_PODS):
        raise ValueError("invalid nodes range %r" % spec)
    return lo, hi


def job_identity(
    default_job: str = "", default_pod: str = ""
) -> Tuple[str, str]:
    """``(job_id, pod_id)`` from the environment, with caller-chosen
    fallbacks for off-cluster use.

    This is the ONE place `EDL_JOB_ID`/`EDL_POD_ID` are read with a
    component-specific default: every other reader uses the empty
    string, and the env-registry lint flags conflicting literal
    defaults — the chaos trainee's ``("chaos", "nopod")`` storeless
    identity lives in its *call* here, not in a divergent env read.
    An empty env value counts as unset, matching every call site's
    ``env.get(...) or fallback`` behavior before this helper existed."""
    env = os.environ
    return (
        env.get("EDL_JOB_ID", "") or default_job,
        env.get("EDL_POD_ID", "") or default_pod,
    )


def local_device_count() -> int:
    override = os.environ.get("EDL_DEVICES_PER_PROC")
    if override:
        return int(override)
    import jax  # deliberate lazy import

    return jax.local_device_count()


class JobEnv:
    """Launcher-side job configuration (args override env)."""

    def __init__(
        self,
        job_id: Optional[str] = None,
        store_endpoint: Optional[str] = None,
        nodes_range: Optional[str] = None,
        nproc_per_node: Optional[int] = None,
        log_dir: Optional[str] = None,
        ckpt_path: Optional[str] = None,
        compile_cache_dir: Optional[str] = None,
    ) -> None:
        env = os.environ
        self.job_id = job_id or env.get("EDL_JOB_ID", "")
        if not self.job_id:
            raise ValueError("job_id required (flag --job_id or env EDL_JOB_ID)")
        self.store_endpoint = store_endpoint or env.get("EDL_STORE_ENDPOINT", "")
        self.min_nodes, self.max_nodes = _parse_nodes_range(
            nodes_range or env.get("EDL_NODES_RANGE", "1:%d" % MAX_PODS)
        )
        self.nproc_per_node = int(
            nproc_per_node or env.get("EDL_NPROC_PER_NODE", "1")
        )
        self.log_dir = log_dir or env.get("EDL_LOG_DIR", "")
        self.ckpt_path = ckpt_path or env.get("EDL_CKPT_PATH", "")
        # Persistent XLA compilation cache shared by every worker the job
        # ever spawns. Stop-resume elasticity restarts all JAX processes
        # per resize; without this each stage recompiles from scratch and
        # spawn->first-step dominates resize downtime. Job-scoped default
        # (stable across restarts on the host); "none" disables.
        if compile_cache_dir is None:
            compile_cache_dir = env.get("EDL_COMPILE_CACHE_DIR", "")
        if not compile_cache_dir:
            import tempfile

            # Per-user root: on a multi-tenant host another user owning a
            # shared /tmp/edl_xla_cache would make makedirs fail at startup,
            # and loading serialized executables from a world-writable dir
            # is a cache-poisoning surface.
            uid = os.getuid() if hasattr(os, "getuid") else 0
            compile_cache_dir = os.path.join(
                tempfile.gettempdir(), "edl_xla_cache-%d" % uid, self.job_id
            )
        self.compile_cache_dir = (
            "" if compile_cache_dir == "none" else compile_cache_dir
        )

    def __repr__(self) -> str:
        return (
            "JobEnv(job_id=%r, store=%r, nodes=%d:%d, nproc=%d)"
            % (
                self.job_id,
                self.store_endpoint,
                self.min_nodes,
                self.max_nodes,
                self.nproc_per_node,
            )
        )


class WorkerEnv:
    """Worker-process-side view of the env injected by the process manager.

    The training entrypoint reads this (via :func:`edl_tpu.train.init`) to
    join the job: global rank, world size, the JAX coordinator endpoint,
    and the stage token of the cluster generation it belongs to.
    """

    VARS = (
        "EDL_JOB_ID",
        "EDL_POD_ID",
        "EDL_STAGE",
        "EDL_WORKER_RANK",
        "EDL_WORKER_RANK_IN_POD",
        "EDL_NUM_WORKERS",
        "EDL_COORDINATOR",
        "EDL_WORKER_ENDPOINTS",
        "EDL_STORE_ENDPOINT",
        "EDL_CKPT_PATH",
        "EDL_CKPT_LOCAL_DIR",
        "EDL_COMPILE_CACHE_DIR",
        "EDL_NODES_RANGE",
        "EDL_NPROC_PER_NODE",
    )

    def __init__(self) -> None:
        env = os.environ
        self.job_id = env.get("EDL_JOB_ID", "")
        self.pod_id = env.get("EDL_POD_ID", "")
        self.stage = env.get("EDL_STAGE", "")
        self.global_rank = int(env.get("EDL_WORKER_RANK", "0"))
        self.rank_in_pod = int(env.get("EDL_WORKER_RANK_IN_POD", "0"))
        self.world_size = int(env.get("EDL_NUM_WORKERS", "1"))
        self.coordinator = env.get("EDL_COORDINATOR", "")
        self.worker_endpoints: List[str] = [
            e for e in env.get("EDL_WORKER_ENDPOINTS", "").split(",") if e
        ]
        self.store_endpoint = env.get("EDL_STORE_ENDPOINT", "")
        self.ckpt_path = env.get("EDL_CKPT_PATH", "")
        # pod-local checkpoint tier (checkpoint/replicate.py): derived
        # per pod by the launcher from EDL_CKPT_LOCAL_BASE; empty = the
        # classic single-tier layout where ckpt_path is the only dir
        self.ckpt_local_dir = env.get("EDL_CKPT_LOCAL_DIR", "")
        self.compile_cache_dir = env.get("EDL_COMPILE_CACHE_DIR", "")
        # the elastic window, worker-visible (the AOT resize ladder
        # derives its neighbor worlds from it). Absent or malformed =
        # a window pinned to the current world — the ladder is a no-op.
        try:
            self.nproc_per_node = max(1, int(env.get("EDL_NPROC_PER_NODE", "1") or 1))
        except ValueError:
            self.nproc_per_node = 1
        pods = max(1, self.world_size // self.nproc_per_node)
        try:
            self.min_nodes, self.max_nodes = _parse_nodes_range(
                env["EDL_NODES_RANGE"]
            )
        except (KeyError, ValueError):
            self.min_nodes = self.max_nodes = pods

    @property
    def is_rank0(self) -> bool:
        return self.global_rank == 0

    @staticmethod
    def present() -> bool:
        """True when running under the edl_tpu launcher."""
        return "EDL_WORKER_RANK" in os.environ and "EDL_JOB_ID" in os.environ
