"""edl_tpu.chaos — deterministic fault injection + recovery conformance.

The paper's value proposition is that training *survives* membership
change; this package is what makes that claim regression-testable
instead of demo-grade:

- :mod:`edl_tpu.chaos.plane` — named fault points compiled into the
  control-plane hot paths (wire codec, store client/server, launcher,
  worker spawn, checkpoint manager, data dispatcher, distill pipeline),
  armed via ``EDL_CHAOS`` env or the job's ``chaos/`` store keyspace,
  with seeded deterministic schedules and zero overhead when disarmed;
- :mod:`edl_tpu.chaos.scenario` — named fault scenarios (worker kill,
  store blip, corrupt checkpoint, slow RPC tail, teacher failover)
  composed against the resize harness;
- :mod:`edl_tpu.chaos.invariants` — the recovery-conformance checker
  that reads the obs metrics/spans and the store and asserts training
  actually recovered (resumed step, shard accounting, checkpoint
  fallback, bounded downtime).

Run scenarios via ``python tools/chaos_run.py --scenario all --seed 0``.
"""

from edl_tpu.chaos.plane import (  # noqa: F401
    ChaosDrop,
    FaultPoint,
    arm_from_env,
    arm_from_store,
    chaos_prefix,
    configure,
    disarm,
    fault_point,
    points,
    publish_spec,
)
