"""The chaos trainee: a minimal elastic training script built for audit.

Runs under the real launcher (spawned per stage with the ``EDL_*`` env
contract) and exercises the real recovery machinery — StoreClient,
CheckpointManager (restore falls back past corrupt versions), WorkerMeter
telemetry, the obs plane — while keeping the "model" trivial so scenarios
fit tier-1 time budgets. Every externally-visible effect is recorded in
the job's ``chaos/progress/`` keyspace so
:mod:`edl_tpu.chaos.invariants` can audit the run:

- ``progress/shard/{step:05d}``   -> json, committed exactly-once via
  put-if-absent by the stage's rank-0 (the data-shard ledger);
- ``progress/step.w{rank}``       -> latest completed step (live cursor);
- ``progress/restore.{stage}.w{rank}`` -> json {restored, fallbacks, ts}
  written right after checkpoint restore;
- ``progress/done.{stage}.w{rank}``    -> json {step, replays} on clean exit;
- ``progress/drained.{stage}.w{rank}`` -> json {step, ts} when a drain
  notice was honored (emergency checkpoint on rank 0, then DRAINED_EXIT).

The trainee also rides the health plane end to end: it publishes per-step
heartbeats through :class:`edl_tpu.train.context.HealthMonitor` (so the
launcher's straggler watchdog can see it) and checks the drain notice
between steps (so ``preempt-drain`` exercises the real worker-side path).
It likewise rides the profiling plane: a real jitted step feeds
:class:`edl_tpu.obs.profile.StepTelemetry` (windowed-MFU/roofline gauges
on its /metrics endpoint) and a
:class:`~edl_tpu.obs.profile.CaptureController` honors ``profile/request``
store keys with a bounded ``jax.profiler`` window — the 2-pod CPU e2e
drill in tests/test_profile.py drives exactly this path.

Scenario knobs (env): ``EDL_CHAOS_TOTAL_STEPS`` (default 16),
``EDL_CHAOS_CKPT_EVERY`` (4), ``EDL_CHAOS_STEP_TIME`` seconds (0.05).

The per-step fault point ``train.step`` is where worker-kill scenarios
strike (ctx: step, rank, stage) and where straggler scenarios wedge a
rank with a long ``delay``. The step itself is REAL gradient descent on
a tiny quadratic (loss ``0.5*mean((w - target)^2)``, contraction 0.9
per step): loss and gradient norms decay smoothly, so the numerics
plane rides every drill — the probe publishes ``edl_train_*`` gauges,
checkpoints carry continuity fingerprints, and the
``train.grad.corrupt`` fault point (ctx: step, rank, stage; payload:
the host gradient bytes) lets the grad-corrupt red drill poison one
step's gradient and prove the nan-detected/loss-spike tripwires fire.
"""

from __future__ import annotations

import json
import os
import sys
import time

from edl_tpu.chaos import plane as chaos
from edl_tpu.store.client import StoreClient, connect_store
from edl_tpu.utils.log import get_logger

logger = get_logger("chaos.trainee")

_FP_STEP = chaos.fault_point(
    "train.step",
    "one training step in the chaos trainee: kill (worker SIGKILL "
    "mid-step), delay (straggler), or drop",
)

_FP_GRAD = chaos.fault_point(
    "train.grad.corrupt",
    "the trainee's per-step gradient bytes: corrupt flips bits in the "
    "update a rank is about to apply — the numerics plane's nan/spike "
    "tripwires must catch it (grad-corrupt red drill)",
)

_FP_OOM = chaos.fault_point(
    "train.mem.oom",
    "the trainee's step dispatch as the allocator sees it: drop stands "
    "in for RESOURCE_EXHAUSTED — the fire site re-raises it as the "
    "synthetic device-OOM the memory plane's forensics guard must "
    "intercept (hbm-oom red drill)",
)


class _Env:
    """The slice of JobEnv the WorkerMeter/HealthMonitor need, from env."""

    def __init__(self) -> None:
        from edl_tpu.cluster.job_env import job_identity

        # the storeless self-identity ("chaos"/"nopod") is a call-site
        # default, not a divergent env read — see job_identity
        self.job_id, self.pod_id = job_identity("chaos", "nopod")
        self.store_endpoint = os.environ.get("EDL_STORE_ENDPOINT", "")
        self.stage = os.environ.get("EDL_STAGE", "nostage")
        self.global_rank = int(os.environ.get("EDL_WORKER_RANK", "0"))
        self.rank_in_pod = int(os.environ.get("EDL_WORKER_RANK_IN_POD", "0"))
        self.world_size = int(os.environ.get("EDL_NUM_WORKERS", "1"))


def _put(client: StoreClient, key: str, value: bytes) -> None:
    client.retrying("put", k=key, v=value, l=0)


def main() -> int:
    t_main = time.monotonic()
    env = _Env()
    client = connect_store(env.store_endpoint, timeout=5.0)
    chaos.arm_from_env("worker", client=client, job_id=env.job_id)

    # goodput ledger + flight recorder: the trainee accounts for every
    # second of its life exactly like ElasticTrainer does, so chaos runs
    # produce the attribution evidence goodput_accounted audits
    from edl_tpu.obs import events as obs_events
    from edl_tpu.obs import goodput as obs_goodput
    from edl_tpu.obs import trace as obs_trace

    # distributed tracing: this process's whole spawn->restore->first-
    # step window is one restage-trace segment chain (trace id derived
    # from the stage token, like train.context.init does)
    obs_trace.begin_process_op("restage", env.stage, rank=str(env.global_rank))
    obs_goodput.enter("restage", cause="spawn")

    from edl_tpu.checkpoint.manager import (
        _M_RESTORE_FALLBACKS,
        CheckpointManager,
        TrainStatus,
    )
    from edl_tpu.obs import http as obs_http
    from edl_tpu.utils import telemetry

    import jax.numpy as jnp

    total = int(os.environ.get("EDL_CHAOS_TOTAL_STEPS", "16"))
    ckpt_every = int(os.environ.get("EDL_CHAOS_CKPT_EVERY", "4"))
    step_time = float(os.environ.get("EDL_CHAOS_STEP_TIME", "0.05"))
    prefix = chaos.chaos_prefix(env.job_id) + "progress/"
    stage8 = env.stage[:8]
    rank = env.global_rank

    obs = obs_http.start_from_env("worker")
    if obs is not None:
        obs_http.register_endpoint(
            client, env.job_id, "worker", "w%d" % rank, obs.endpoint
        )

    # profiling plane, end to end on the audited miniature: the "train
    # step" is a real jitted computation so the cost-extraction path, the
    # windowed-MFU gauge, and store-driven jax.profiler capture windows
    # are all exercised by the same 2-pod CPU jobs the chaos drills run
    from edl_tpu.obs import profile as obs_profile

    import jax
    import numpy as np

    # real training semantics for the numerics plane: gradient descent
    # on a quadratic bowl. grad = (w - target)/8 (the mean), lr 0.8 ->
    # (w - target) contracts by exactly 0.9 per step, so loss decays
    # x0.81/step and the gradient norm stays orders of magnitude above
    # the grad-stall floor for any drill length — smooth enough that
    # monitor-clean stays silent, real enough that a corrupted gradient
    # overflows f32 within one step.
    _TARGET = jnp.arange(8, dtype=jnp.float32)
    _LR = jnp.float32(0.8)

    @jax.jit
    def _train_step(w):
        return jax.value_and_grad(
            lambda p: 0.5 * jnp.mean((p - _TARGET) ** 2)
        )(w)

    step_telemetry = obs_profile.StepTelemetry()
    step_telemetry.set_cost(
        obs_profile.step_cost(_train_step, jnp.zeros(8, jnp.float32))
    )
    # memory plane, end to end on the audited miniature: the jitted
    # step's compile-time plan is harvested and published (mem/plan/N —
    # the fit gate's evidence), the census/watermark gauges ride the
    # /metrics endpoint the monitor scrapes, and the oom_guard around
    # step dispatch is what the hbm-oom red drill strikes
    from edl_tpu.obs import memory as obs_memory

    mem_plane = obs_memory.MemoryPlane(
        stage=stage8, rank=rank, client=client, job_id=env.job_id
    )
    mem_plane.harvest(
        _train_step, jnp.zeros(8, jnp.float32), world=env.world_size
    )
    try:
        capture = obs_profile.CaptureController(env, telemetry=step_telemetry)
    except Exception as exc:  # noqa: BLE001 — profiling is best-effort
        logger.warning("capture plane unavailable: %s", exc)
        capture = None

    mngr = CheckpointManager(
        os.environ.get("EDL_CKPT_PATH", "/tmp/edl-chaos-ckpt"), max_to_keep=3
    )
    template = {"w": jnp.zeros(8, jnp.float32)}
    # restage-trace segment: everything from the launcher's spawn stamp
    # (or, storeless, process entry) to the restore — interpreter start,
    # jax import, obs mount, store connect — is boot cost the critical
    # path must attribute, not an untraced gap
    boot_t0 = t_main
    try:
        age = time.time() - float(os.environ.get("EDL_SPAWN_TS", ""))
        if 0.0 < age < 3600.0:
            boot_t0 = time.monotonic() - age
    except ValueError:
        pass
    obs_trace.get_tracer().record(
        "worker_boot", boot_t0, time.monotonic() - boot_t0, rank=rank
    )
    state, status = mngr.restore(template)
    t_setup = time.monotonic()
    start = int(status.step) if status is not None else 0
    # numerics plane: throttled gauge export + cross-replica digest +
    # the resume-continuity check against the restored fingerprint
    from edl_tpu.obs import numerics as obs_numerics

    probe = None
    if obs_numerics.enabled():
        probe = obs_numerics.NumericsProbe(
            rank=rank, client=client, job_id=env.job_id
        )
        if status is not None:
            probe.expect((status.meta or {}).get("numerics"))
    _put(
        client,
        "%srestore.%s.w%d" % (prefix, stage8, rank),
        json.dumps(
            {
                "restored": start,
                "fallbacks": _M_RESTORE_FALLBACKS.value(),
                "stage": stage8,
                "ts": time.time(),
            }
        ).encode(),
    )
    logger.info(
        "trainee stage=%s rank=%d world=%d: starting at step %d/%d",
        stage8, rank, env.world_size, start, total,
    )

    # the health plane's worker half: heartbeats for the straggler
    # watchdog, the preempt watch for graceful drain. Best-effort — a
    # trainee without a reachable store still trains.
    from edl_tpu.train.context import DRAINED_EXIT, HealthMonitor

    try:
        health = HealthMonitor(env)
    except Exception as exc:  # noqa: BLE001
        logger.warning("health monitor unavailable: %s", exc)
        health = None

    meter = telemetry.WorkerMeter(env, batch_per_step=1, client=client)
    replays = 0
    # restage-trace segment: restore-ledger publish + health monitor +
    # meter setup — the last hop before training resumes
    obs_trace.get_tracer().record(
        "worker_setup", t_setup, time.monotonic() - t_setup, rank=rank
    )
    obs_goodput.enter("train", cause="resumed")
    for step in range(start, total):
        t_step0 = time.monotonic()
        if health is not None and health.drain_notice:
            # graceful drain: emergency checkpoint (rank 0 owns the ckpt
            # dir, same as periodic saves), record the drain, exit clean
            obs_trace.begin_process_op("drain", env.pod_id)
            obs_goodput.enter("drain", cause="preempt")
            if rank == 0:
                mngr.emergency_save(
                    state,
                    TrainStatus(step=step, world_size=env.world_size,
                                meta={"emergency": True}),
                    budget_s=health.drain_budget_left(),
                )
                # with a pod-local tier armed, the emergency version must
                # not die with this pod: push it to a peer holder inside
                # whatever drain budget remains (no-op single-tier)
                mngr.emergency_replicate(health.drain_budget_left())
            _put(
                client,
                "%sdrained.%s.w%d" % (prefix, stage8, rank),
                json.dumps({"step": step, "ts": time.time()}).encode(),
            )
            health.record_drained(step)
            health.close()
            if probe is not None:
                probe.close()
            if capture is not None:
                capture.close()
            mem_plane.close()
            step_telemetry.close()
            meter.close()
            mngr.close()
            client.close()
            obs_goodput.close(cause="drained")
            logger.info(
                "trainee stage=%s rank=%d DRAINED at step %d", stage8, rank, step
            )
            return DRAINED_EXIT
        if _FP_STEP.armed:
            _FP_STEP.fire(step=step, rank=rank, stage=stage8)
        # close the previous step's train interval so the scraped
        # edl_goodput_seconds_total{state="train"} counter advances per
        # step — the live rate signal the monitor plane's
        # goodput-degraded rule watches (the real trainer loop gets this
        # for free from its train<->data_wait flap)
        obs_goodput.enter("train", cause="step")
        # per-step black-box marker: bounds a SIGKILLed rank's open
        # goodput interval to one step, and IS the "last recorded state"
        # the flight-recorder acceptance test looks for
        obs_events.record("step", step=step, rank=rank, stage=stage8)
        time.sleep(step_time)  # the pacing; the jitted step is the compute
        w = state["w"]
        try:
            with mem_plane.oom_guard(step=step):
                if _FP_OOM.armed:
                    try:
                        _FP_OOM.fire(step=step, rank=rank, stage=stage8)
                    except ConnectionError as drop:
                        # the drop action IS the allocator saying no:
                        # the real path surfaces device OOM as an
                        # XlaRuntimeError whose stable cross-version
                        # part is the RESOURCE_EXHAUSTED message text
                        raise RuntimeError(
                            "RESOURCE_EXHAUSTED: Out of memory while "
                            "dispatching chaos train step (injected: %s)"
                            % drop
                        ) from drop
                loss, grad = _train_step(w)
        except RuntimeError as exc:
            if not obs_memory.is_oom(exc):
                raise
            # the guard already captured forensics. A real allocator OOM
            # leaves the PROCESS alive — restaging is the loop's call —
            # so mirror the real worker's exit: emergency-checkpoint
            # (rank 0 owns the dir), hold the /metrics endpoint up for
            # one monitor sweep so the terminal oom counter is scraped,
            # then die and let the launcher restage the gang.
            if rank == 0:
                mngr.emergency_save(
                    state,
                    TrainStatus(step=step, world_size=env.world_size,
                                meta={"oom": True}),
                    budget_s=5.0,
                )
            time.sleep(float(os.environ.get("EDL_CHAOS_OOM_GRACE", "2.0")))
            raise
        if _FP_GRAD.armed:
            # the red drill's injection site: the fault plane sees (and
            # may corrupt) the actual gradient bytes this rank is about
            # to apply. Any damage is amplified to a guaranteed f32
            # overflow so the nan/spike tripwires have an unambiguous
            # signal within one step.
            raw = np.asarray(grad, dtype=np.float32).tobytes()
            out = _FP_GRAD.fire(payload=raw, step=step, rank=rank, stage=stage8)
            if out is not None and bytes(out) != raw:
                grad = jnp.asarray(
                    np.frombuffer(bytes(out), dtype=np.float32).copy()
                ) * jnp.float32(1e38)
        state = {"w": w - _LR * grad}
        if probe is not None:
            probe.on_step(
                step,
                obs_numerics.device_bundle(
                    loss, {"w": grad}, {"w": w}, {"w": state["w"]}
                ),
            )
        step_telemetry.observe_step()
        mem_plane.on_step(step)
        if step == start:
            # first completed step: the restage op's closing segment
            # (recorded while the op context is live, so it stitches)
            from edl_tpu.obs.trace import get_tracer

            get_tracer().record(
                "first_step", t_step0, time.monotonic() - t_step0,
                step=step,
            )
            obs_trace.end_process_op()
        if capture is not None:
            capture.on_step(
                sync=lambda s=state: jax.block_until_ready(s["w"])
            )
        if rank == 0:
            # the data-shard ledger: exactly-once via put-if-absent; a
            # replayed step (resume behind the pre-crash cursor) finds
            # its shard already committed — counted, never duplicated
            created = client.retrying(
                "put_absent",
                k="%sshard/%05d" % (prefix, step),
                v=json.dumps({"stage": stage8, "ts": time.time()}).encode(),
                l=0,
            )["created"]
            if not created:
                replays += 1
        meter.step()
        if health is not None:
            health.heartbeat(step, dt=step_time)
        _put(client, "%sstep.w%d" % (prefix, rank), str(step).encode())
        if rank == 0 and (step + 1) % ckpt_every == 0:
            mngr.save(state, TrainStatus(step=step + 1, world_size=env.world_size))
            mngr.wait()
    if rank == 0 and total % ckpt_every != 0:
        mngr.save(state, TrainStatus(step=total, world_size=env.world_size))
        mngr.wait()
    if health is not None:
        health.close()
    if probe is not None:
        probe.close()
    if capture is not None:
        capture.close()
    mem_plane.close()
    step_telemetry.close()
    meter.close()
    _put(
        client,
        "%sdone.%s.w%d" % (prefix, stage8, rank),
        json.dumps({"step": total, "replays": replays, "ts": time.time()}).encode(),
    )
    mngr.close()
    client.close()
    obs_goodput.close(cause="complete")
    logger.info("trainee stage=%s rank=%d COMPLETE at step %d", stage8, rank, total)
    return 0


if __name__ == "__main__":
    sys.exit(main())
