"""Recovery-conformance invariants: did the system actually recover?

Every chaos scenario ends by assembling :class:`Evidence` — the job's
``chaos/progress/`` ledger, the PR-1 telemetry keyspace
(drain/killed/published/first_step events), the chaos injection log, and
/metrics snapshots harvested from live obs endpoints during the run — and
asserting invariants over it. A scenario is green only when every
invariant holds; each failure names the evidence that contradicts the
recovery claim.

The invariants encode the paper's elastic contract:

- **completed**: the job reached its target step despite the fault;
- **shards exactly-once**: the data-shard ledger (put-if-absent commits)
  covers ``0..N-1`` with no gap and no duplicate — membership change
  neither skipped nor double-processed data;
- **resumed, not restarted**: some post-fault incarnation restored a
  checkpoint at step > 0;
- **bounded rework**: replayed steps are bounded by the checkpoint
  interval per recovery (stop-resume may re-run the tail since the last
  checkpoint, never more);
- **checkpoint fallback**: with the newest version corrupted, restore
  fell back to an older good version (and said so);
- **bounded, attributed downtime**: each recovery transition's
  drain -> first_step interval is under budget, with the kill/publish
  decomposition recorded;
- **fault visibility**: every injected fault left a ledger entry and an
  ``edl_chaos_faults_injected_total`` series where the process survived.

The health plane (PR 4) adds drain/straggler conformance:

- **drained before deadline**: a preemption notice ("preempt" event) was
  answered by a worker "drained" event inside the drain budget;
- **proactive drain**: the drain-token bump followed the notice within a
  couple of loop passes — NOT after a lease expiry or the failure-grace
  window (the no-grace-hold-on-drain property);
- **lost work bounded**: a post-drain restore landed at or past the step
  cursor observed at notice time (the emergency checkpoint was used);
- **straggler ejected within deadline**: the wedge injection was followed
  by a "straggler" ejection event inside the watchdog deadline;
- **zero stragglers**: the false-positive drill — a slow control plane
  must eject nobody.

The monitor plane (PR 6) adds alerting conformance:

- **alerts fired**: the in-rig ``edl_monitord`` published a firing
  transition for the named rule within a bounded latency of the fault;
- **no false alerts**: the clean control run (``monitor-clean``)
  published no alert record at all.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.chaos.plane import chaos_prefix
from edl_tpu.utils.log import get_logger

logger = get_logger("chaos.invariants")


@dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""

    def __str__(self) -> str:
        return "%s %s%s" % (
            "PASS" if self.ok else "FAIL",
            self.name,
            (": " + self.detail) if self.detail else "",
        )


@dataclass
class Evidence:
    """Everything a scenario collected about one run."""

    progress: Dict = field(default_factory=dict)   # read_progress() output
    telemetry: Dict = field(default_factory=dict)  # utils.telemetry.collect()
    chaos_log: List[Dict] = field(default_factory=list)
    metrics: Dict[str, Dict] = field(default_factory=dict)  # target -> scrape


# -- evidence collection ------------------------------------------------------


def read_progress(client, job_id: str) -> Dict:
    """Parse the trainee's ``chaos/progress/`` ledger back into dicts."""
    prefix = chaos_prefix(job_id) + "progress/"
    rows, _rev = client.range(prefix)
    shards: Dict[int, dict] = {}
    restores: List[dict] = []
    dones: List[dict] = []
    cursors: Dict[str, int] = {}
    malformed = 0
    for key, value, _c, _m in rows:
        rest = key[len(prefix):]
        try:
            if rest.startswith("shard/"):
                shards[int(rest[len("shard/"):])] = json.loads(value)
            elif rest.startswith("restore."):
                restores.append({"key": rest, **json.loads(value)})
            elif rest.startswith("done."):
                dones.append({"key": rest, **json.loads(value)})
            elif rest.startswith("step."):
                cursors[rest[len("step."):]] = int(value)
        except (ValueError, TypeError):
            malformed += 1
    return {
        "shards": shards,
        "restores": restores,
        "dones": dones,
        "cursors": cursors,
        "malformed": malformed,
    }


def read_chaos_log(path: str) -> List[Dict]:
    """Parse the crash-safe injection ledger (one JSON object per line)."""
    entries: List[Dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    pass
    except OSError:
        pass
    return entries


class MetricsHarvester:
    """Scrape every store-registered obs endpoint on a timer, keeping the
    LAST successful scrape per target — processes here die on purpose, so
    conformance must be checked against the freshest pre-death sample."""

    def __init__(self, client, job_id: str, interval: float = 0.4) -> None:
        self._client = client
        self._job_id = job_id
        self._interval = interval
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._latest: Dict[str, Dict] = {}
        self._thread = threading.Thread(
            target=self._loop, name="edl-chaos-harvest", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        from edl_tpu.obs import http as obs_http

        while not self._stop.wait(self._interval):
            try:
                targets = obs_http.discover_endpoints(self._client, self._job_id)
            except Exception:  # noqa: BLE001 — store may be mid-blip
                continue
            for who, info in targets.items():
                endpoint = info.get("endpoint")
                if not endpoint:
                    continue
                try:
                    scraped = obs_http.fetch_metrics(endpoint, timeout=1.0)
                except Exception:  # noqa: BLE001 — dead targets are expected
                    continue
                with self._lock:
                    self._latest[who] = scraped

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._latest.items()}

    def stop(self) -> Dict[str, Dict]:
        self._stop.set()
        self._thread.join(timeout=3)
        return self.snapshot()


def _metric_total(evidence: Evidence, name: str, label_substr: str = "") -> float:
    total = 0.0
    for scrape in evidence.metrics.values():
        for labels, value in scrape.get(name, {}).items():
            if label_substr in labels:
                total += value
    return total


# -- invariants ---------------------------------------------------------------


def completed(evidence: Evidence, total_steps: int) -> InvariantResult:
    steps = [int(d.get("step", -1)) for d in evidence.progress.get("dones", [])]
    ok = any(s == total_steps for s in steps)
    return InvariantResult(
        "completed",
        ok,
        "done records at steps %s (want %d)" % (sorted(set(steps)), total_steps),
    )


def shards_exactly_once(evidence: Evidence, total_steps: int) -> InvariantResult:
    got = set(evidence.progress.get("shards", {}))
    want = set(range(total_steps))
    missing = sorted(want - got)
    extra = sorted(got - want)
    ok = not missing and not extra
    return InvariantResult(
        "shards_exactly_once",
        ok,
        "%d/%d committed%s%s"
        % (
            len(got & want),
            total_steps,
            (", missing %s" % missing[:8]) if missing else "",
            (", unexpected %s" % extra[:8]) if extra else "",
        ),
    )


def resumed_past_prefault_step(
    evidence: Evidence, prefault_step: Optional[int] = None
) -> InvariantResult:
    """Some incarnation RESTORED (restored > 0) and the job's final step
    reached past where training was when the fault struck."""
    restores = evidence.progress.get("restores", [])
    resumed = [r for r in restores if int(r.get("restored", 0)) > 0]
    final = max(
        [int(d.get("step", 0)) for d in evidence.progress.get("dones", [])],
        default=0,
    )
    floor = prefault_step if prefault_step is not None else 1
    ok = bool(resumed) and final >= floor
    return InvariantResult(
        "resumed_past_prefault_step",
        ok,
        "restores at %s, final step %d (pre-fault floor %s)"
        % (sorted(int(r.get("restored", 0)) for r in restores), final, floor),
    )


def replay_bounded(evidence: Evidence, ckpt_every: int) -> InvariantResult:
    """Stop-resume may re-run at most the tail since the last checkpoint,
    once per recovery — a recovery is a restaged GENERATION, so count
    distinct stages among the restore records (per-rank records of one
    stage are one recovery, not several)."""
    replays = sum(
        int(d.get("replays", 0)) for d in evidence.progress.get("dones", [])
    )
    stages = {
        r.get("stage") for r in evidence.progress.get("restores", [])
    }
    recoveries = max(1, len(stages) - 1)
    bound = ckpt_every * recoveries
    ok = replays <= bound
    return InvariantResult(
        "replay_bounded",
        ok,
        "%d replayed steps (bound %d = %d ckpt_every x %d recoveries)"
        % (replays, bound, ckpt_every, recoveries),
    )


def checkpoint_fell_back(
    evidence: Evidence, corrupted_step: int
) -> InvariantResult:
    """After the newest version was corrupted, some restore skipped it:
    fallbacks counted, and the restored step is OLDER than the corrupt one."""
    hits = [
        r
        for r in evidence.progress.get("restores", [])
        if int(r.get("fallbacks", 0)) > 0
        and int(r.get("restored", 0)) < corrupted_step
    ]
    return InvariantResult(
        "checkpoint_fell_back",
        bool(hits),
        "restores %s (corrupt version at step %d)"
        % (
            [(int(r.get("restored", -1)), int(r.get("fallbacks", 0)))
             for r in evidence.progress.get("restores", [])],
            corrupted_step,
        ),
    )


def downtime_bounded(evidence: Evidence, budget_s: float) -> InvariantResult:
    """Every recovery transition (stage with both drain and first_step
    events) kept drain -> first step under budget, with the attribution
    timestamps present."""
    events = evidence.telemetry.get("events", {})
    spans = []
    for stage, evs in events.items():
        if "drain" not in evs or "first_step" not in evs:
            continue
        downtime = max(evs["first_step"].values()) - min(evs["drain"].values())
        spans.append((stage[:8], round(downtime, 3)))
    worst = max((d for _, d in spans), default=None)
    ok = bool(spans) and worst is not None and worst <= budget_s
    return InvariantResult(
        "downtime_bounded",
        ok,
        "transitions %s (budget %.1fs)" % (spans, budget_s),
    )


def fault_injected(
    evidence: Evidence, point: str, action: str, at_least: int = 1
) -> InvariantResult:
    """The fault plane actually struck: the crash-safe ledger has the
    injection(s) this scenario scheduled."""
    hits = [
        e
        for e in evidence.chaos_log
        if e.get("point") == point and e.get("action") == action
    ]
    return InvariantResult(
        "fault_injected[%s@%s]" % (action, point),
        len(hits) >= at_least,
        "%d ledger entr%s (want >= %d)"
        % (len(hits), "y" if len(hits) == 1 else "ies", at_least),
    )


def retries_observed(evidence: Evidence, at_least: int = 1) -> InvariantResult:
    """The shared retry path (utils/retry.py) absorbed the fault:
    edl_rpc_retries_total advanced on some live endpoint."""
    total = _metric_total(evidence, "edl_rpc_retries_total")
    return InvariantResult(
        "retries_observed",
        total >= at_least,
        "edl_rpc_retries_total=%d across %d scraped targets (want >= %d)"
        % (int(total), len(evidence.metrics), at_least),
    )


def faults_visible_in_metrics(
    evidence: Evidence, point: str, extra_registry=None
) -> InvariantResult:
    """edl_chaos_faults_injected_total{point=...} advanced somewhere a
    scrape (or the in-process registry, for runner-hosted components)
    could see it."""
    total = _metric_total(
        evidence, "edl_chaos_faults_injected_total", 'point="%s"' % point
    )
    if extra_registry is not None:
        inst = extra_registry.get("edl_chaos_faults_injected_total")
        if inst is not None:
            for line in inst.render():
                if 'point="%s"' % point in line:
                    total += float(line.rpartition(" ")[2])
    return InvariantResult(
        "faults_visible_in_metrics[%s]" % point,
        total >= 1,
        "counter total %d for point %s" % (int(total), point),
    )


def metric_advanced(
    evidence: Evidence, name: str, at_least: float = 1, label_substr: str = ""
) -> InvariantResult:
    """A named counter advanced on some scraped endpoint during the run
    (the harvester keeps the last pre-death sample per target)."""
    total = _metric_total(evidence, name, label_substr)
    return InvariantResult(
        "metric_advanced[%s]" % name,
        total >= at_least,
        "total %g across %d scraped targets (want >= %g)"
        % (total, len(evidence.metrics), at_least),
    )


def promoted_within(
    promote_s: Optional[float], budget_s: float
) -> InvariantResult:
    """A standby took over inside the failover budget (None = it never
    promoted at all)."""
    ok = promote_s is not None and promote_s <= budget_s
    return InvariantResult(
        "promoted_within",
        ok,
        "promotion took %s (budget %.1fs)"
        % ("%.2fs" % promote_s if promote_s is not None else "—never—", budget_s),
    )


def acked_write_survived(
    value: Optional[bytes],
    expected: bytes,
    mod_rev: int,
    acked_rev: int,
) -> InvariantResult:
    """A write the OLD primary acknowledged is present on the promoted
    store with its original mod revision — the journal-before-ack +
    live-stream contract held through the failover."""
    ok = value == expected and mod_rev == acked_rev
    return InvariantResult(
        "acked_write_survived",
        ok,
        "value=%r rev=%d (acked %r rev=%d)" % (value, mod_rev, expected, acked_rev),
    )


def stale_primary_fenced(
    fenced_epoch: Optional[int],
    probe_refused: bool,
    new_epoch: int,
) -> InvariantResult:
    """The resurrected old primary fenced itself on the promoted
    primary's epoch and refused a fresh client's write."""
    ok = (
        fenced_epoch is not None
        and fenced_epoch >= new_epoch
        and probe_refused
    )
    return InvariantResult(
        "stale_primary_fenced",
        ok,
        "fenced_by=%s (promoted epoch %d), probe write %s"
        % (
            fenced_epoch,
            new_epoch,
            "refused" if probe_refused else "ACCEPTED",
        ),
    )


def watch_resumed_exactly_once(
    events, shard_prefix: str, total_steps: int
) -> InvariantResult:
    """A watch held across the failover saw every shard commit exactly
    once, with no gap (a gap would force a resync marker) and no
    duplicate — the promoted standby's replicated history covered the
    client's resume revision."""
    resyncs = sum(1 for e in events if e.type == "resync")
    shards: List[int] = []
    for e in events:
        if e.type == "put" and e.key.startswith(shard_prefix):
            try:
                shards.append(int(e.key[len(shard_prefix):]))
            except ValueError:
                pass
    want = list(range(total_steps))
    ok = resyncs == 0 and sorted(shards) == want and len(shards) == len(set(shards))
    return InvariantResult(
        "watch_resumed_exactly_once",
        ok,
        "%d/%d shard events (%d dup, %d resync)"
        % (
            len(set(shards) & set(want)),
            total_steps,
            len(shards) - len(set(shards)),
            resyncs,
        ),
    )


def _events_of_kind(evidence: Evidence, kind: str) -> List[float]:
    """All timestamps of one event kind across every stage, sorted."""
    out: List[float] = []
    for evs in evidence.telemetry.get("events", {}).values():
        out.extend(evs.get(kind, {}).values())
    return sorted(out)


def drained_before_deadline(
    evidence: Evidence, budget_s: float
) -> InvariantResult:
    """Every preemption notice was answered by a worker 'drained' event
    within the drain budget (the emergency-checkpoint window held)."""
    preempts = _events_of_kind(evidence, "preempt")
    draineds = _events_of_kind(evidence, "drained")
    if not preempts:
        return InvariantResult(
            "drained_before_deadline", False, "no preempt event recorded"
        )
    worst = None
    for p in preempts:
        after = [d for d in draineds if d >= p - 0.2]
        if not after:
            return InvariantResult(
                "drained_before_deadline",
                False,
                "preempt at %.2f never drained (drained events: %d)"
                % (p, len(draineds)),
            )
        delta = min(after) - p
        worst = delta if worst is None else max(worst, delta)
    ok = worst is not None and worst <= budget_s
    return InvariantResult(
        "drained_before_deadline",
        ok,
        "worst notice->drained %.2fs (budget %.1fs, %d notice(s))"
        % (worst if worst is not None else -1, budget_s, len(preempts)),
    )


def proactive_drain(evidence: Evidence, bound_s: float) -> InvariantResult:
    """No-grace-hold-on-drain: the drain-token bump landed within
    ``bound_s`` of the preemption notice. A reactive system (lease expiry
    after the pod dies, or a worker-failure grace hold) cannot get there —
    its drain trails the notice by at least drain-budget + TTL."""
    preempts = _events_of_kind(evidence, "preempt")
    drains = _events_of_kind(evidence, "drain")
    if not preempts:
        return InvariantResult("proactive_drain", False, "no preempt event")
    p0 = min(preempts)
    after = [d for d in drains if d >= p0 - 0.2]
    if not after:
        return InvariantResult(
            "proactive_drain", False,
            "no drain event followed the notice at %.2f" % p0,
        )
    delta = min(after) - p0
    return InvariantResult(
        "proactive_drain",
        delta <= bound_s,
        "notice->drain %.2fs (bound %.1fs)" % (delta, bound_s),
    )


def lost_work_bounded(
    evidence: Evidence, cursor_at_notice: int, slack_steps: int = 1
) -> InvariantResult:
    """The emergency checkpoint was actually USED: some post-drain restore
    landed at or past the step cursor observed when the notice was sent
    (minus the one in-flight step a drain may legitimately drop)."""
    restores = [
        int(r.get("restored", 0))
        for r in evidence.progress.get("restores", [])
    ]
    best = max(restores, default=0)
    floor = max(0, cursor_at_notice - slack_steps)
    return InvariantResult(
        "lost_work_bounded",
        best >= floor,
        "best restore at step %d, notice cursor %d (floor %d)"
        % (best, cursor_at_notice, floor),
    )


def straggler_ejected_within(
    evidence: Evidence, budget_s: float
) -> InvariantResult:
    """The wedge (a long train.step delay injection) was answered by a
    watchdog ejection ('straggler' event) inside the deadline budget."""
    wedges = sorted(
        float(e["ts"])
        for e in evidence.chaos_log
        if e.get("point") == "train.step" and e.get("action") == "delay"
    )
    ejections = _events_of_kind(evidence, "straggler")
    if not wedges:
        return InvariantResult(
            "straggler_ejected_within", False, "no wedge injected"
        )
    if not ejections:
        return InvariantResult(
            "straggler_ejected_within", False,
            "wedge at %.2f never ejected" % wedges[0],
        )
    delta = min(e for e in ejections) - wedges[0]
    return InvariantResult(
        "straggler_ejected_within",
        0 <= delta <= budget_s,
        "wedge->ejection %.2fs (budget %.1fs)" % (delta, budget_s),
    )


def zero_stragglers(evidence: Evidence) -> InvariantResult:
    """False-positive drill: nobody was ejected and nobody drained."""
    ejections = _events_of_kind(evidence, "straggler")
    preempts = _events_of_kind(evidence, "preempt")
    ok = not ejections and not preempts
    return InvariantResult(
        "zero_stragglers",
        ok,
        "%d straggler ejection(s), %d preempt notice(s) (want 0/0)"
        % (len(ejections), len(preempts)),
    )


def drained_exit_clean(
    exit_code: Optional[int], t_exit_s: Optional[float], budget_s: float
) -> InvariantResult:
    """The noticed pod left with the DRAINED exit code, inside the drain
    budget — not killed, not crash-looped, not grace-held."""
    from edl_tpu.cluster.contract import DRAINED_EXIT

    ok = (
        exit_code == DRAINED_EXIT
        and t_exit_s is not None
        and t_exit_s <= budget_s
    )
    return InvariantResult(
        "drained_exit_clean",
        ok,
        "exit code %s in %s (want %d within %.1fs)"
        % (
            exit_code,
            "%.2fs" % t_exit_s if t_exit_s is not None else "—",
            DRAINED_EXIT,
            budget_s,
        ),
    )


def goodput_accounted(
    flight_events: List[Dict], tolerance: float = 0.05
) -> InvariantResult:
    """The goodput ledger accounts for the run: across every recording
    process, the seconds the ledger CLAIMS (state intervals) cover the
    lifetime each process was OBSERVED for (first to last flight record)
    within ``tolerance`` — in aggregate and per lane — and the run
    actually trained. Note the job table itself partitions wall-clock by
    construction (uncovered slices become ``down``), so comparing its
    sum to the window would be vacuous; the teeth are claimed-vs-
    observed, where a ledger that loses seconds shows a hole."""
    from edl_tpu.obs import goodput as obs_goodput

    if not flight_events:
        return InvariantResult(
            "goodput_accounted", False, "no flight-recorder events"
        )
    att = obs_goodput.attribute(flight_events)
    wall = att["wall_s"]
    if wall <= 0:
        return InvariantResult(
            "goodput_accounted", False, "degenerate window (wall=%.3fs)" % wall
        )
    # claimed-vs-observed, per lane and in aggregate: a lane's intervals
    # are contiguous by construction, so its observed lifetime is
    # first-interval start to last-record end; any shortfall is a second
    # the ledger lost
    gaps = []
    claimed = 0.0
    observed = 0.0
    for (comp, pid), sp in obs_goodput.process_intervals(flight_events).items():
        life = sp[-1][1] - sp[0][0]
        acc = sum(b - a for a, b, _s in sp)
        claimed += acc
        observed += life
        if life > 0 and (life - acc) > tolerance * max(life, 1.0):
            gaps.append(("%s-%d" % (comp, pid), round(life - acc, 3)))
    sum_ok = observed > 0 and (observed - claimed) <= tolerance * observed
    trained = att["states"].get("train", 0.0) > 0
    pct = {
        s: round(100.0 * v / wall, 1)
        for s, v in sorted(att["states"].items())
    }
    ok = sum_ok and not gaps and trained
    return InvariantResult(
        "goodput_accounted",
        ok,
        "%.1fs wall -> %s (claimed %.1fs of %.1fs observed)%s%s"
        % (
            wall,
            pct,
            claimed,
            observed,
            "" if trained else ", NO train seconds",
            (", lane gaps %s" % gaps) if gaps else "",
        ),
    )


def alert_fired(
    alerts: Optional[Dict[str, Dict]],
    rule: str,
    after_ts: float,
    within_s: float,
) -> InvariantResult:
    """The monitor plane noticed the fault: the named rule has a firing
    transition inside ``[after_ts, after_ts + within_s]`` (the published
    record keeps the full firing history, so a later teardown re-fire or
    an earlier legitimate firing — e.g. a grow-restage gap — cannot mask
    the verdict either way)."""
    record = (alerts or {}).get(rule)
    if record is None:
        return InvariantResult(
            "alerts_fired[%s]" % rule,
            False,
            "no alert record for rule (have: %s)" % sorted(alerts or {}),
        )
    firings = [float(t) for t in record.get("firings", [])]
    # strictly post-fault: both stamps come from time.time() on one host
    # and a fault-caused firing can only trail its cause — a pre-fault
    # grace window would let an unrelated earlier RESOLVED firing pass
    # the check
    hits = [t for t in firings if after_ts <= t <= after_ts + within_s]
    latency = min((t - after_ts for t in hits), default=None)
    # A firing episode that BEGAN before the fault and never resolved
    # also covers it: the monitor was continuously reporting the
    # degradation through the fault window, so no new transition can
    # exist (hysteresis holds one episode open). Seen on loaded CPU
    # rigs where a slow-start dip runs straight into the fault's gap;
    # the monitor-clean scenario keeps this from excusing a rule that
    # simply fires always.
    since = record.get("since")
    resolved_ts = record.get("resolved_ts")
    covered = (
        isinstance(since, (int, float))
        and since <= after_ts
        and (
            record.get("state") == "firing"  # still open at collection
            or (
                isinstance(resolved_ts, (int, float))
                and resolved_ts >= after_ts  # resolved only after it
            )
        )
    )
    return InvariantResult(
        "alerts_fired[%s]" % rule,
        bool(hits) or covered,
        "fired %d time(s)%s; fault at %.2f, budget %.1fs (firings %s%s)"
        % (
            len(firings),
            (", %.2fs after the fault" % latency) if latency is not None else "",
            after_ts,
            within_s,
            [round(t - after_ts, 2) for t in firings[:8]],
            "; episode open across the fault since %.2f" % (since - after_ts)
            if covered else "",
        ),
    )


def alert_fired_any(
    alerts: Optional[Dict[str, Dict]],
    rules: List[str],
    after_ts: float,
    within_s: float,
) -> InvariantResult:
    """The monitor plane noticed the fault through ANY of the named
    rules. Scenarios pass the set of alerts the fault class
    deterministically produces: on a fast CPU rig the goodput dip of a
    restage can be SHORTER than the rate rule's detection granularity
    (the recovery outrunning the monitor is a feature — the sharded
    control plane shortened drain->first-step below the paced window),
    while dead-endpoint / restart-detected fire structurally on a
    killed or respawned worker. The goodput rule's own firing logic
    keeps its dedicated red drill in tests/test_monitor.py."""
    results = [alert_fired(alerts, rule, after_ts, within_s) for rule in rules]
    ok = any(r.ok for r in results)
    hit = next((r for r in results if r.ok), None)
    return InvariantResult(
        "alerts_fired_any[%s]" % "|".join(rules),
        ok,
        hit.detail if hit is not None
        else "; ".join("%s: %s" % (r.name, r.detail) for r in results),
    )


def no_false_alerts(alerts: Optional[Dict[str, Dict]]) -> InvariantResult:
    """The zero-false-positive control: a clean run publishes NO alert
    record at all (records exist only after a first firing)."""
    fired = sorted(
        "%s(x%d)" % (r.get("rule", name), int(r.get("fired_count", 1)))
        for name, r in (alerts or {}).items()
    )
    return InvariantResult(
        "no_false_alerts",
        not fired,
        "no alert ever fired" if not fired else "fired: %s" % fired,
    )


def critical_path_traced(
    trace_spans,
    flight_events: List[Dict],
    tolerance: float = 0.3,
    slack_s: float = 1.25,
) -> InvariantResult:
    """The distributed-tracing plane stitched the restage end to end:
    the LAST completed restage operation (the post-fault generation) has

    - a cross-process trace (>= 2 distinct processes contributed — the
      drain-trigger/leader side AND the respawned worker side),
    - zero orphan segments (every span's parent resolves inside the
      trace: the wire-level ``tc`` propagation and the deterministic op
      roots actually linked up), and
    - a critical path whose covered seconds match the goodput ledger's
      restage-lane accounting for the SAME processes over the same
      pre-first-step window within ``tolerance`` (+ an absolute CPU-rig
      slack) — the trace's claim about where the downtime went agrees
      with the black-box evidence.
    """
    from edl_tpu.obs import tracepath

    spans = list(trace_spans)
    ops = tracepath.extract_ops(spans, op="restage")
    done = [o for o in ops if o.complete]
    if not done:
        return InvariantResult(
            "critical_path_traced",
            False,
            "no completed restage trace (%d linked spans, %d restage "
            "trace(s))" % (len(spans), len(ops)),
        )
    ot = done[-1]
    problems: List[str] = []
    if len(ot.processes) < 2:
        problems.append("single-process trace (%s)" % ot.processes)
    if ot.orphans:
        problems.append(
            "%d orphan segment(s): %s"
            % (len(ot.orphans), sorted({s.name for s in ot.orphans})[:6])
        )
    cmp = tracepath.goodput_compare(ot, flight_events)
    if cmp is None:
        problems.append("no goodput lane evidence for the traced processes")
    else:
        bound = max(tolerance * cmp["window_s"], slack_s)
        if abs(cmp["delta_s"]) > bound:
            problems.append(
                "path %.2fs vs restage lane %.2fs (|delta| %.2fs > "
                "bound %.2fs)"
                % (cmp["path_s"], cmp["lane_s"], abs(cmp["delta_s"]), bound)
            )
    detail = "op %s: %d segment(s) across %s, window %.2fs" % (
        ot.trace_id,
        len(ot.segments),
        ot.processes,
        ot.t1 - ot.t0,
    )
    if cmp is not None:
        detail += ", path %.2fs vs lane %.2fs" % (cmp["path_s"], cmp["lane_s"])
    return InvariantResult(
        "critical_path_traced",
        not problems,
        detail if not problems else "; ".join(problems) + " [" + detail + "]",
    )


def peer_tier_restored(
    evidence: Evidence,
    flight_events: List[Dict],
    after_ts: float,
) -> InvariantResult:
    """Shared-FS-free recovery: every checkpoint restore AFTER the fault
    came from the PEER tier — zero durable-tier reads — with the flight
    records (which survive the killed pod) naming the tier per restore
    and ``edl_ckpt_restores_total{tier="peer"}`` advanced on a scraped
    endpoint as the metric-side corroboration."""
    post = [
        e for e in flight_events
        if e.get("event") == "ckpt_restore"
        and float(e.get("ts", 0.0)) > after_ts
    ]
    tiers = sorted({str(e.get("tier", "?")) for e in post})
    metric_peer = _metric_total(
        evidence, "edl_ckpt_restores_total", 'tier="peer"'
    )
    # "local" may legitimately appear AFTER a peer restore already
    # landed the assembled step in the local tier (a later restage
    # re-reads it there) — still zero shared-FS reads. "durable" is the
    # read this invariant outlaws.
    ok = (
        bool(post)
        and "peer" in tiers
        and "durable" not in tiers
        and metric_peer >= 1
    )
    return InvariantResult(
        "peer_tier_restored",
        ok,
        "%d post-fault restore(s) from tier(s) %s; "
        "edl_ckpt_restores_total{tier=peer}=%d scraped"
        % (len(post), tiers or ["-none-"], int(metric_peer)),
    )


def restore_segment_traced(trace_spans) -> InvariantResult:
    """The restore hop is visible on the edl-trace restage critical
    path: the LAST completed restage operation contains a
    ``ckpt_restore`` segment (the worker-side tier-ladder hop)."""
    from edl_tpu.obs import tracepath

    ops = [
        o for o in tracepath.extract_ops(list(trace_spans), op="restage")
        if o.complete
    ]
    if not ops:
        return InvariantResult(
            "restore_segment_traced", False, "no completed restage trace"
        )
    ot = ops[-1]
    hits = [s for s in ot.segments if s.name == "ckpt_restore"]
    return InvariantResult(
        "restore_segment_traced",
        bool(hits),
        "op %s: %d ckpt_restore segment(s) among %d"
        % (ot.trace_id, len(hits), len(ot.segments)),
    )


def single_stage(evidence: Evidence) -> InvariantResult:
    """The fault was absorbed WITHOUT a restage: exactly one generation
    was ever published."""
    events = evidence.telemetry.get("events", {})
    published = [s[:8] for s, evs in events.items() if "published" in evs]
    return InvariantResult(
        "single_stage",
        len(published) == 1,
        "published stages %s" % published,
    )


def multiple_stages(evidence: Evidence, at_least: int = 2) -> InvariantResult:
    """Recovery went through a restage: a new generation was published
    after the fault."""
    events = evidence.telemetry.get("events", {})
    published = [s[:8] for s, evs in events.items() if "published" in evs]
    return InvariantResult(
        "restaged",
        len(published) >= at_least,
        "published stages %s (want >= %d)" % (published, at_least),
    )


def run_archived(
    bundle: Optional[str], index_path: str
) -> InvariantResult:
    """The run-archive plane (PR 14) worked: every scenario must leave a
    COMPLETE bundle behind — the manifest parses, its rollups are
    non-empty, and one index row was appended (the crash-safe
    ``runs/index.jsonl`` line edl-report lists and gates on)."""
    name = "run_archived"
    if not bundle or not os.path.isdir(bundle):
        return InvariantResult(name, False, "no bundle archived")
    manifest_path = os.path.join(bundle, "run.json")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        return InvariantResult(
            name, False, "manifest unreadable: %s" % exc
        )
    if not isinstance(manifest, dict):
        return InvariantResult(name, False, "manifest is not an object")
    rollups = manifest.get("rollups") or {}
    if not rollups:
        return InvariantResult(name, False, "manifest has no rollups")
    bundle_name = os.path.basename(bundle.rstrip(os.sep))
    from edl_tpu.obs import events as obs_events

    indexed = any(
        row.get("bundle") == bundle_name
        for row in obs_events.read_records(index_path)
    )
    if not indexed:
        return InvariantResult(
            name, False,
            "no index row for %s in %s" % (bundle_name, index_path),
        )
    return InvariantResult(
        name, True,
        "bundle %s: %d rollups, indexed" % (bundle_name, len(rollups)),
    )


def numerics_continuous(flight_events: List[Dict]) -> InvariantResult:
    """The resize continuity sentinel held (PR 16, obs/numerics): every
    restored worker's probe re-checked the checkpoint's stamped loss
    against its first post-resume loss and found training continuous
    (``numerics_resume`` flight records, ``ok`` true). A drill that
    restarts workers MUST leave at least one such record — no record
    means the sentinel never ran, which is its own failure."""
    name = "numerics_continuous"
    resumes = [
        e for e in flight_events if e.get("event") == "numerics_resume"
    ]
    if not resumes:
        return InvariantResult(
            name, False, "no numerics_resume records: sentinel never ran"
        )
    bad = [e for e in resumes if not e.get("ok")]
    return InvariantResult(
        name,
        not bad,
        "%d resume check(s), %d failed%s"
        % (
            len(resumes),
            len(bad),
            "" if not bad else ": " + "; ".join(
                str(e.get("detail", "?")) for e in bad[:3]
            ),
        ),
    )


def nonfinite_recorded(
    flight_events: List[Dict], at_least: int = 1
) -> InvariantResult:
    """The corruption left a black-box trace: fsync'd ``nonfinite`` /
    ``loss_spike`` flight instants (the ones edl-timeline overlays on
    the goodput lanes) were recorded by the probe."""
    hits = [
        e
        for e in flight_events
        if e.get("event") in ("nonfinite", "loss_spike")
    ]
    return InvariantResult(
        "nonfinite_recorded",
        len(hits) >= at_least,
        "%d nonfinite/loss_spike flight record(s) (want >= %d)"
        % (len(hits), at_least),
    )


def oom_forensics_captured(flight_events: List[Dict]) -> InvariantResult:
    """The OOM left admissible evidence: an fsync'd ``oom`` flight
    instant was recorded AND its forensics bundle is on disk and
    parseable — the error text, the active memory plan, a census of what
    was resident, and the stage watermark. The crash-safety contract is
    that the bundle lands (tmp + fsync + replace) BEFORE the error
    propagates into drain/restage, so it must survive the process
    death that follows."""
    name = "oom_forensics_captured"
    ooms = [e for e in flight_events if e.get("event") == "oom"]
    if not ooms:
        return InvariantResult(name, False, "no oom flight instant recorded")
    problems: List[str] = []
    parsed = 0
    for e in ooms:
        bundle = e.get("bundle") or ""
        if not bundle:
            problems.append("oom instant without a bundle path")
            continue
        try:
            with open(bundle) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            problems.append("bundle %s unreadable: %s" % (bundle, exc))
            continue
        missing = [
            k for k in ("error", "census", "plan", "peak_bytes")
            if k not in doc
        ]
        if missing:
            problems.append("bundle %s missing %s" % (bundle, missing))
            continue
        if "RESOURCE_EXHAUSTED" not in str(doc.get("error", "")):
            problems.append(
                "bundle %s error is not an OOM: %r"
                % (bundle, str(doc.get("error", ""))[:80])
            )
            continue
        parsed += 1
    return InvariantResult(
        name,
        parsed >= 1,
        "%d oom instant(s), %d parseable bundle(s)%s"
        % (len(ooms), parsed,
           ("; problems: %s" % "; ".join(problems[:4])) if problems else ""),
    )


# -- scale plane --------------------------------------------------------------


def scale_reconcile_latencies(flight_events: List[Dict]) -> Dict[int, float]:
    """Per decision seq, the decision->restage latency: the scaler's
    fsync'd ``scale_decision`` instant to the FIRST launcher
    ``scale_reconcile`` record carrying the same seq (both wall-clock
    ``ts`` on the same host — the chaos rig runs everything locally)."""
    decided: Dict[int, float] = {}
    for e in flight_events:
        if e.get("event") == "scale_decision" and e.get("seq") is not None:
            decided.setdefault(int(e["seq"]), float(e.get("ts", 0.0)))
    out: Dict[int, float] = {}
    for e in flight_events:
        if e.get("event") != "scale_reconcile" or e.get("seq") is None:
            continue
        seq = int(e["seq"])
        if seq in decided and seq not in out:
            out[seq] = float(e.get("ts", 0.0)) - decided[seq]
    return out


def scale_decision_latency(
    flight_events: List[Dict], budget_s: float
) -> InvariantResult:
    """The scale plane's end-to-end contract: at least one autoscale
    decision was reconciled into a published stage, and every
    reconciled decision closed inside the latency budget."""
    name = "scale_decision_latency"
    lat = scale_reconcile_latencies(flight_events)
    if not lat:
        return InvariantResult(
            name, False, "no scale_decision->scale_reconcile pair recorded"
        )
    worst = max(lat.values())
    return InvariantResult(
        name,
        worst <= budget_s,
        "%d decision(s) reconciled, worst %.1fs (budget %.1fs)"
        % (len(lat), worst, budget_s),
    )


def autoscale_goodput_bounded(
    achieved: float, oracle: float, loss_bound_pct: float
) -> InvariantResult:
    """Scheduler quality vs the offline oracle: the realized world-size
    schedule (publish/drain flight records evaluated under the same
    goodput model and signal trace) must capture at least
    ``100 - loss_bound_pct`` percent of the oracle's integral — the
    oracle re-decides instantly and restages for free, so the loss is
    exactly what hysteresis, cooldown, and restage gaps cost."""
    name = "autoscale_goodput_bounded"
    if oracle <= 0:
        return InvariantResult(name, False, "degenerate oracle (<= 0)")
    loss = 100.0 * (1.0 - achieved / oracle)
    return InvariantResult(
        name,
        loss <= loss_bound_pct,
        "goodput loss %.1f%% vs oracle (bound %.0f%%)"
        % (loss, loss_bound_pct),
    )


def gang_atomic_worlds(
    flight_events: List[Dict], min_world: int
) -> InvariantResult:
    """Gang atomicity: every stage the launcher PUBLISHED for this job
    ran at >= its min world — grow/shrink transitions never stranded
    the collective below its floor (pods held or released, all or
    nothing). Exactly 0 is legal: the pause marker an autoscale
    preempt-to-0 publishes (nobody runs; not a stranded collective)."""
    sizes = [
        int(e.get("pods", 0))
        for e in flight_events
        if e.get("event") == "publish"
    ]
    low = [s for s in sizes if 0 < s < min_world]
    return InvariantResult(
        "gang_atomic_worlds",
        bool(sizes) and not low,
        "%d published stage(s), worlds %s (floor %d)"
        % (len(sizes), sorted(set(sizes)), min_world),
    )


# -- store consistency plane (chaos/consistency.py) ---------------------------


def _consistency_detail(report, *checks: str) -> str:
    bad = report.violations_of(*checks)
    head = "%d ops, %d reads, %d acked writes, %d watch events" % (
        report.ops, report.reads, report.writes_acked,
        report.watch_deliveries,
    )
    if not bad:
        return head
    return "%s; %d violation(s): %s" % (
        head, len(bad),
        "; ".join(v.get("detail", v["check"]) for v in bad[:3]),
    )


def no_stale_reads(report) -> InvariantResult:
    """Every taped read answered with the newest ACKED write at-or-below
    its revision — no stale value, no lost acked write, no value
    mismatch. Vacuous histories fail: a checker that judged nothing
    proves nothing."""
    bad = report.violations_of("stale-read", "value-mismatch")
    ok = not bad and report.reads > 0 and report.writes_acked > 0
    return InvariantResult(
        "no_stale_reads", ok,
        _consistency_detail(report, "stale-read", "value-mismatch"),
    )


def monotonic_session_reads(report) -> InvariantResult:
    """No session watched its own history rewind: per session, a key's
    observed revision never decreased, nothing observed vanished without
    an acked delete, and no read answered below the session floor — even
    with reads hopping between standby leg and primary, across the
    failover."""
    bad = report.violations_of("non-monotonic-session")
    ok = not bad and report.reads > 0
    return InvariantResult(
        "monotonic_session_reads", ok,
        _consistency_detail(report, "non-monotonic-session"),
    )


def watch_gap_free(report) -> InvariantResult:
    """Every taped watch delivered acked writes exactly once in strictly
    increasing revision order — no duplicate, no reorder, no silent gap
    (an honest ``resync`` marker is the one sanctioned gap)."""
    bad = report.violations_of("watch-gap", "watch-duplicate", "watch-order")
    ok = not bad and report.watch_deliveries > 0
    return InvariantResult(
        "watch_gap_free", ok,
        _consistency_detail(
            report, "watch-gap", "watch-duplicate", "watch-order"
        ),
    )


def consistency_anomaly_reproduced(report) -> InvariantResult:
    """RED drill: the checker must CATCH the anomaly the degraded
    configuration (EDL_STORE_MVCC=0, kill inside the semi-sync window)
    provably produces — a checker that stays green here checks
    nothing."""
    ok = bool(report.violations)
    return InvariantResult(
        "consistency_anomaly_reproduced", ok,
        report.summary(),
    )
