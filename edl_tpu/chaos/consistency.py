"""History-checked store consistency: replay the client op tape and
prove the guarantees the MVCC/standby read plane claims — or catch the
anomaly when a drill deliberately breaks them.

The tape (``store.client._OpTape``) records every completed client op
(ok or fail) and every watch delivery as flight-recorder JSONL, one
SESSION (``cid``) per client including its standby read leg. This module
is the Jepsen-style checker over that history, specialized to the
store's revision model — revisions are globally ordered and returned on
every response, so linearizability-class checks reduce to revision
arithmetic instead of NP-hard search:

``no_stale_reads``
    A read answering AS OF revision ``r`` must return, per key, exactly
    the newest ACKED write at-or-below ``r``. An older value, a value
    mismatch, or a missing key is a stale read / lost acked write.
    Failed (indeterminate) writes may or may not appear — never
    required, never forbidden.

``monotonic_session_reads``
    Within one session, a key's observed ``mod_rev`` never decreases and
    an observed key never vanishes without an acked delete — the
    session's view of history must not rewind, even when its reads hop
    between a standby leg and the primary, across a failover.

``watch_gap_free``
    Per watch: delivered revisions strictly increase (no duplicates, no
    reordering) and every acked write to the watched prefix inside the
    delivered window arrives exactly once. A ``resync`` marker forgives
    the gap it announces (that is its contract) and resets the window.

Checks are DOMAIN-scOPED to the probe prefix (default ``/cp/``): only
keys every writer of which is on tape are judged, so harness pods
churning their own keyspaces can never fabricate a verdict.

``ConsistencyChurn`` is the probe the store scenarios run while faults
fire: one taped session doing mixed put/get/range traffic plus a watch,
with a final retrying read-back audit so the last acked write per key is
always judged by at least one read.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.utils.log import get_logger

logger = get_logger("chaos.consistency")

PROBE_PREFIX = "/cp/"

# checker verdicts land in the flight dir as this event (edl-timeline
# renders them as instants on the run's causal lane)
VERDICT_EVENT = "consistency_verdict"


@dataclass
class ConsistencyReport:
    """The checker's verdict over one run's op tape."""

    ops: int = 0                  # taped domain ops (ok + fail)
    reads: int = 0                # ok domain reads judged (get + range)
    writes_acked: int = 0         # acked domain writes (put/cas/del)
    writes_indeterminate: int = 0
    watch_deliveries: int = 0     # domain watch events delivered
    sessions: int = 0
    unverified: int = 0           # reads the tape cannot judge (no
    #                               acked write at-or-below their asof)
    violations: List[Dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def violations_of(self, *checks: str) -> List[Dict]:
        return [v for v in self.violations if v["check"] in checks]

    def summary(self) -> str:
        return (
            "%d ops (%d reads, %d acked writes, %d watch events, "
            "%d sessions): %s"
            % (
                self.ops, self.reads, self.writes_acked,
                self.watch_deliveries, self.sessions,
                "CONSISTENT" if self.ok
                else "%d violation(s): %s" % (
                    len(self.violations),
                    "; ".join(
                        "%s %s" % (v["check"], v.get("key", v.get("wid", "")))
                        for v in self.violations[:6]
                    ),
                ),
            )
        )


def _in_domain(doc: Dict, prefix: str) -> bool:
    target = doc.get("k") or doc.get("p")
    return isinstance(target, str) and target.startswith(prefix)


def check_history(
    flight_events: List[Dict], prefix: str = PROBE_PREFIX
) -> ConsistencyReport:
    """Run every consistency check over the tape records found in
    ``flight_events`` (the merged flight-recorder read of a run's
    workdir), judging only keys under ``prefix``."""
    report = ConsistencyReport()
    ops = [
        e for e in flight_events
        if e.get("event") == "store_op" and _in_domain(e, prefix)
    ]
    report.ops = len(ops)
    report.sessions = len({o.get("cid") for o in ops})

    # -- the acked write history, per key ---------------------------------
    # (rev, digest, alive) per key, rev-sorted. cas only when it swapped;
    # del is a tombstone. Failed writes are indeterminate: counted, never
    # part of the required history.
    writes: Dict[str, List[tuple]] = {}
    for op in ops:
        if op.get("op") not in ("put", "cas", "del"):
            continue
        if not op.get("ok"):
            report.writes_indeterminate += 1
            continue
        if op["op"] == "cas" and not op.get("sw"):
            continue  # an unswapped cas wrote nothing
        if op["op"] == "del" and not op.get("nd"):
            continue  # deleted nothing
        rev = op.get("r")
        if rev is None:
            continue
        report.writes_acked += 1
        writes.setdefault(op["k"], []).append(
            (rev, op.get("d"), op["op"] != "del")
        )
    for chain in writes.values():
        chain.sort()

    def newest_acked(key: str, asof: int) -> Optional[tuple]:
        best = None
        for entry in writes.get(key, ()):
            if entry[0] <= asof:
                best = entry
            else:
                break
        return best

    def judge_read(op: Dict, key: str, mr: int, digest, asof: int) -> None:
        """One (key, mod_rev, digest) observation at revision ``asof``."""
        expect = newest_acked(key, asof)
        if expect is None:
            if mr:
                report.unverified += 1  # only indeterminate writes ≤ asof
            return
        erev, edig, alive = expect
        if not mr:  # read said: key absent
            if alive:
                report.violations.append({
                    "check": "stale-read", "key": key, "asof": asof,
                    "seq": op.get("seq"), "cid": op.get("cid"),
                    "detail": "acked write rev %d invisible (read absent "
                              "at asof %d)" % (erev, asof),
                })
            return
        if mr < erev:
            report.violations.append({
                "check": "stale-read", "key": key, "asof": asof,
                "seq": op.get("seq"), "cid": op.get("cid"),
                "detail": "returned rev %d, but acked rev %d <= asof %d"
                          % (mr, erev, asof),
            })
        elif mr == erev and not alive:
            report.violations.append({
                "check": "stale-read", "key": key, "asof": asof,
                "seq": op.get("seq"), "cid": op.get("cid"),
                "detail": "returned tombstoned rev %d" % mr,
            })
        elif mr == erev and edig is not None and digest != edig:
            report.violations.append({
                "check": "value-mismatch", "key": key, "asof": asof,
                "seq": op.get("seq"), "cid": op.get("cid"),
                "detail": "rev %d returned digest %s, acked %s"
                          % (mr, digest, edig),
            })

    # -- check 1: stale reads / lost acked writes -------------------------
    for op in ops:
        if not op.get("ok") or op.get("pin"):
            continue
        asof = op.get("r")
        if asof is None:
            continue
        if op["op"] == "get":
            report.reads += 1
            judge_read(op, op["k"], op.get("mr") or 0, op.get("d"), asof)
        elif op["op"] == "range":
            report.reads += 1
            rows = {k: (mr, d) for k, mr, d in op.get("rows") or ()}
            for k, (mr, d) in rows.items():
                judge_read(op, k, mr, d, asof)
            if not op.get("trunc"):
                # coverage: an acked-alive key missing from the snapshot
                # is a lost write, same as a get answering absent
                for key, chain in writes.items():
                    if key in rows or not key.startswith(op["p"]):
                        continue
                    expect = newest_acked(key, asof)
                    if expect is not None and expect[2]:
                        report.violations.append({
                            "check": "stale-read", "key": key, "asof": asof,
                            "seq": op.get("seq"), "cid": op.get("cid"),
                            "detail": "acked rev %d missing from range "
                                      "snapshot at asof %d"
                                      % (expect[0], asof),
                        })

    # -- check 2: monotonic session reads ---------------------------------
    # per (cid, key): observed mod_rev must never decrease, and an
    # observed key must not vanish without an acked delete above it
    for cid in sorted({o.get("cid") for o in ops}):
        floor = 0          # highest revision any op of this session reported
        seen: Dict[str, int] = {}  # key -> highest observed mod_rev
        for op in sorted(
            (o for o in ops if o.get("cid") == cid),
            key=lambda o: o.get("seq") or 0,
        ):
            if not op.get("ok"):
                continue
            r = op.get("r")
            if op["op"] in ("get", "range") and not op.get("pin"):
                if r is not None and r < floor:
                    report.violations.append({
                        "check": "non-monotonic-session", "cid": cid,
                        "seq": op.get("seq"),
                        "detail": "read answered at rev %d below the "
                                  "session floor %d" % (r, floor),
                    })
                obs = (
                    [(op["k"], op.get("mr") or 0)] if op["op"] == "get"
                    else [(k, mr) for k, mr, _d in op.get("rows") or ()]
                )
                for key, mr in obs:
                    prev = seen.get(key, 0)
                    if mr and mr < prev:
                        report.violations.append({
                            "check": "non-monotonic-session", "cid": cid,
                            "key": key, "seq": op.get("seq"),
                            "detail": "key regressed from rev %d to %d"
                                      % (prev, mr),
                        })
                    elif not mr and prev:
                        dels = [
                            e for e in writes.get(key, ())
                            if not e[2] and e[0] > prev
                        ]
                        if not dels and r is not None and r >= prev:
                            report.violations.append({
                                "check": "non-monotonic-session",
                                "cid": cid, "key": key,
                                "seq": op.get("seq"),
                                "detail": "key seen at rev %d vanished "
                                          "with no acked delete" % prev,
                            })
                    if mr:
                        seen[key] = max(prev, mr)
            if r is not None:
                floor = max(floor, r)

    # -- check 3: watch gap-free ------------------------------------------
    starts = {
        (e.get("cid"), e.get("cli"), e.get("wid")): e
        for e in flight_events
        if e.get("event") == "store_watch" and _in_domain(e, prefix)
    }
    deliveries: Dict[tuple, List[List]] = {k: [] for k in starts}
    for e in flight_events:
        if e.get("event") != "store_watch_ev":
            continue
        wkey = (e.get("cid"), e.get("cli"), e.get("wid"))
        if wkey in deliveries:
            deliveries[wkey].extend(e.get("evs") or [])
    for wkey, start in starts.items():
        evs = deliveries[wkey]
        wid = "%s/w%s" % (start.get("cid"), start.get("wid"))
        floor = start.get("r0") or 0  # deliveries begin above this
        seen_revs: set = set()
        last = floor
        max_delivered = floor
        for etype, key, rev in evs:
            if etype == "resync":
                # the server compacted past the resume point and said so:
                # everything at-or-below the marker is forgiven
                floor = max(floor, rev)
                last = max(last, rev)
                seen_revs.clear()
                continue
            report.watch_deliveries += 1
            if rev in seen_revs:
                report.violations.append({
                    "check": "watch-duplicate", "wid": wid, "key": key,
                    "detail": "rev %d delivered twice" % rev,
                })
            elif rev < last:
                report.violations.append({
                    "check": "watch-order", "wid": wid, "key": key,
                    "detail": "rev %d delivered after rev %d" % (rev, last),
                })
            seen_revs.add(rev)
            last = max(last, rev)
            max_delivered = max(max_delivered, rev)
        # gaps: every acked write inside (floor, max_delivered] to the
        # watched prefix must have been delivered — later writes may
        # still be in flight when the tape ends, so they are not judged
        wprefix = start.get("p") or prefix
        for key, chain in writes.items():
            if not key.startswith(wprefix):
                continue
            for rev, _d, _alive in chain:
                if floor < rev <= max_delivered and rev not in seen_revs:
                    report.violations.append({
                        "check": "watch-gap", "wid": wid, "key": key,
                        "detail": "acked rev %d inside delivered window "
                                  "(%d, %d] never delivered"
                                  % (rev, floor, max_delivered),
                    })
    return report


def record_verdict(report: ConsistencyReport, flight_dir: str) -> None:
    """Drop the checker's verdict into the run's flight dir (fsync'd) so
    edl-timeline renders it as an instant and the archive carries it."""
    from edl_tpu.obs.events import FlightRecorder

    rec = FlightRecorder(flight_dir, component="consistency")
    try:
        rec.record(
            VERDICT_EVENT, fsync=True,
            ok=report.ok,
            ops=report.ops,
            reads=report.reads,
            writes_acked=report.writes_acked,
            watch_deliveries=report.watch_deliveries,
            violations=report.violations[:32],
            summary=report.summary(),
        )
    finally:
        rec.close()


class ConsistencyChurn:
    """The scenarios' consistency probe: one taped session of mixed
    put/get/range traffic plus a live watch against ``endpoints``,
    running in a daemon thread while the scenario injects faults. Op
    failures are expected mid-fault and simply taped (indeterminate);
    ``stop()`` ends the loop and runs a retrying read-back audit so the
    final acked write of every key is judged by at least one read."""

    def __init__(
        self,
        endpoints: str,
        tape_dir: str,
        prefix: str = PROBE_PREFIX,
        read_mode: str = "leader",
        keys: int = 4,
        period_s: float = 0.02,
    ) -> None:
        from edl_tpu.store.client import StoreClient

        self.prefix = prefix
        self._keys = ["%sk%d" % (prefix, i) for i in range(max(1, keys))]
        self._period = period_s
        self._client = StoreClient(
            endpoints, timeout=3.0, read_mode=read_mode,
            op_tape_dir=tape_dir,
        )
        self._watch = None
        self._watch_seen: List = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="edl-consistency-churn", daemon=True
        )
        self._thread.start()

    def _try(self, fn, *args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception:  # noqa: BLE001 — faults are the point; taped
            return None

    def _run(self) -> None:
        got = self._try(self._client.range, self.prefix)
        start_rev = got[1] if got else None
        self._watch = self._try(
            self._client.watch, self.prefix,
            lambda evs: self._watch_seen.extend(evs),
            start_rev=start_rev,
        )
        i = 0
        while not self._stop.is_set():
            key = self._keys[i % len(self._keys)]
            self._try(self._client.put, key, b"v-%d" % i)
            self._try(self._client.get, key)
            if i % 8 == 7:
                self._try(self._client.range, self.prefix)
            i += 1
            self._stop.wait(self._period)

    def stop(self, audit_timeout: float = 20.0) -> None:
        """Stop the loop, run the final read-back audit, close up."""
        self._stop.set()
        self._thread.join(timeout=10.0)
        deadline = time.time() + audit_timeout
        for key in self._keys:
            if time.time() > deadline:
                break
            self._try(
                self._client.retrying, "get", retries=10, k=key
            )
        self._try(self._client.retrying, "range", retries=10, p=self.prefix)
        # let the watch tail drain so the gap check sees the deliveries
        # for every write the audit just confirmed
        time.sleep(0.5)
        if self._watch is not None:
            self._try(self._watch.cancel)
        self._client.close()
