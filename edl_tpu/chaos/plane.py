"""The fault plane: named fault points compiled into control-plane hot paths.

A *fault point* is a module-level handle declared next to the code it can
break::

    _FP_TX = chaos.fault_point("rpc.wire.tx", "corrupt/delay/drop an outgoing frame")

    def pack_frame(payload):
        if _FP_TX.armed:
            _FP_TX.fire(method=...)          # may sleep, raise, corrupt, or _exit
        ...

Disarmed (the default — no ``EDL_CHAOS`` in the env), the entire plane
costs one attribute load per point per pass: ``armed`` is a plain ``False``
until rules attach, so production hot paths pay nothing measurable.

Armed, a point consults its rules. Rules are matched deterministically:
each rule counts the fires that match its ``match`` context filter and
triggers on the ``after``-th matching fire, for ``times`` consecutive
matching fires, gated by a ``prob`` drawn from a per-rule
``random.Random`` seeded from ``(spec seed, rule index)`` — the same seed
always injects the same faults at the same points in the same order.

Spec (JSON, via ``EDL_CHAOS`` inline / ``@file`` / ``store``)::

    {"seed": 0, "rules": [
        {"point": "train.step", "proc": "worker", "action": "kill",
         "match": {"rank": "1"}, "after": 6},
        {"point": "store.client.request", "proc": "launcher",
         "action": "drop", "after": 30, "times": 20},
        {"point": "store.server.dispatch", "proc": "store",
         "action": "delay", "delay_s": 0.05, "prob": 0.3, "times": 0}]}

Rule fields: ``point`` (required), ``action`` (required), ``proc``
(prefix-match against the arming process's name; absent = every process),
``match`` (ctx equality filter, values compared as strings), ``after``
(1-based matching-fire index, default 1), ``times`` (consecutive
triggers, 0 = unlimited, default 1), ``prob`` (default 1.0), ``delay_s``,
``duration_s`` (partition window), ``exit_code`` (kill, default 137).

Actions:

- ``kill``      ``os._exit(exit_code)`` — a machine death, not a clean exit;
- ``delay``     sleep ``delay_s`` in the caller's thread;
- ``drop``      raise :class:`ChaosDrop` (a ``ConnectionError``) — the
  caller's failure handling sees a dead peer;
- ``corrupt``   flip bits in the ``payload`` bytes handed to ``fire`` (the
  caller sends/uses the corrupted copy);
- ``partition`` like ``drop``, but stays active for ``duration_s`` of
  wall clock after the first trigger (a network partition, not one lost
  frame).

Every injection increments ``edl_chaos_faults_injected_total{point,action}``,
records a trace instant (visible in edl-top and merged Chrome traces), and
— because a ``kill`` takes its process's metrics with it — appends one
line to the crash-safe ``EDL_CHAOS_LOG`` file when set.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

from edl_tpu.utils.log import get_logger

logger = get_logger("chaos.plane")

CHAOS_SERVICE = "chaos"
_KILL_EXIT = 137  # what a SIGKILLed process reports

ACTIONS = ("kill", "delay", "drop", "corrupt", "partition")


class ChaosDrop(ConnectionError):
    """Raised by ``drop``/``partition`` — callers see a dead peer."""


def chaos_prefix(job_id: str) -> str:
    return "/%s/%s/" % (job_id, CHAOS_SERVICE)


class _Rule:
    __slots__ = (
        "point", "action", "proc", "match", "after", "times", "prob",
        "delay_s", "duration_s", "exit_code", "_rng", "_matched",
        "_triggered", "_window_until",
    )

    def __init__(self, spec: Dict, seed: int, index: int) -> None:
        self.point = spec["point"]
        self.action = spec["action"]
        if self.action not in ACTIONS:
            raise ValueError("unknown chaos action %r" % self.action)
        self.proc = spec.get("proc", "")
        self.match = {str(k): str(v) for k, v in (spec.get("match") or {}).items()}
        self.after = int(spec.get("after", 1))
        self.times = int(spec.get("times", 1))  # 0 = unlimited
        self.prob = float(spec.get("prob", 1.0))
        self.delay_s = float(spec.get("delay_s", 0.05))
        self.duration_s = float(spec.get("duration_s", 1.0))
        self.exit_code = int(spec.get("exit_code", _KILL_EXIT))
        # deterministic per-rule stream: same (seed, index) -> same draws
        self._rng = random.Random((seed * 1_000_003 + index) & 0xFFFFFFFF)
        self._matched = 0
        self._triggered = 0
        self._window_until = 0.0

    def applies(self, whos) -> bool:
        """``whos``: the component names armed in this process (a process
        can host several — a launcher with an embedded store)."""
        if not self.proc:
            return True
        return any(w.startswith(self.proc) for w in whos)

    def decide(self, ctx: Dict) -> bool:
        """One matching-fire bookkeeping step; True = inject now."""
        for k, v in self.match.items():
            if str(ctx.get(k)) != v:
                return False
        if self.action == "partition" and time.monotonic() < self._window_until:
            return True  # inside an open window every matching fire drops
        self._matched += 1
        if self._matched < self.after:
            return False
        if self.times and self._triggered >= self.times:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self._triggered += 1
        if self.action == "partition":
            # each trigger opens a fresh window: for partition, ``times``
            # counts WINDOWS (0 = unlimited), not individual drops
            self._window_until = time.monotonic() + self.duration_s
        return True


class FaultPoint:
    """One named place where faults can be injected.

    ``armed`` is False until :func:`configure` attaches a rule, so the
    disabled-plane cost at the call site is a single attribute load.
    """

    __slots__ = ("name", "description", "armed", "_rules", "_lock")

    def __init__(self, name: str, description: str) -> None:
        self.name = name
        self.description = description
        self.armed = False
        self._rules: List[_Rule] = []
        self._lock = threading.Lock()

    def fire(self, payload: Optional[bytes] = None, **ctx):
        """Evaluate rules; may sleep, raise, corrupt ``payload``, or exit.

        Returns ``payload`` (corrupted if a ``corrupt`` rule triggered).
        """
        if not self.armed:
            return payload
        with self._lock:
            hits = [r for r in self._rules if r.decide(ctx)]
        for rule in hits:
            payload = _execute(self, rule, payload, ctx)
        return payload


def _execute(point: FaultPoint, rule: _Rule, payload, ctx):
    _note_injection(point, rule, ctx)
    if rule.action == "delay":
        time.sleep(rule.delay_s)
        return payload
    if rule.action in ("drop", "partition"):
        raise ChaosDrop(
            "chaos: %s at %s" % (rule.action, point.name)
        )
    if rule.action == "corrupt":
        if payload is None:
            raise ChaosDrop("chaos: corrupt at %s (no payload)" % point.name)
        mutable = bytearray(payload)
        for i in range(min(4, len(mutable))):  # header bits: a torn frame
            mutable[i] ^= 0xFF
        return bytes(mutable)
    if rule.action == "kill":
        # flush what we can: the log line above is already on disk
        os._exit(rule.exit_code)
    return payload


def _note_injection(point: FaultPoint, rule: _Rule, ctx: Dict) -> None:
    """Make the injection visible BEFORE the fault executes — a kill must
    not erase its own evidence."""
    log_path = os.environ.get("EDL_CHAOS_LOG")
    if log_path:
        try:
            line = json.dumps(
                {
                    "ts": time.time(),
                    "point": point.name,
                    "action": rule.action,
                    "who": _who,
                    "pid": os.getpid(),
                    "ctx": {k: str(v) for k, v in ctx.items()},
                }
            )
            fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, (line + "\n").encode())
            finally:
                os.close(fd)
        except OSError:
            pass
    try:
        from edl_tpu.obs import metrics as obs_metrics
        from edl_tpu.obs import trace as obs_trace

        obs_metrics.counter(
            "edl_chaos_faults_injected_total",
            "faults injected by the chaos plane, by point and action",
        ).inc(point=point.name, action=rule.action)
        obs_trace.get_tracer().instant(
            "chaos_" + rule.action, point=point.name, **{
                k: str(v) for k, v in ctx.items()
            }
        )
    except Exception:  # noqa: BLE001 — observability must not alter the fault
        pass
    logger.warning(
        "chaos: injecting %s at %s (ctx=%s)", rule.action, point.name, ctx
    )


# -- registry ----------------------------------------------------------------

_points: Dict[str, FaultPoint] = {}
_points_lock = threading.Lock()
_pending: List[_Rule] = []  # rules whose point has not been declared yet
_who = os.environ.get("EDL_CHAOS_PROC", "proc")
# every component name arm_from_env/arm_from_store saw in this process: a
# launcher embedding a store hosts BOTH, and arming the second must not
# silently discard the first's rules (rules match against the whole set)
_armed_whos: set = set()


def fault_point(name: str, description: str) -> FaultPoint:
    """Get-or-create the named fault point (module-import time)."""
    with _points_lock:
        point = _points.get(name)
        if point is None:
            point = _points[name] = FaultPoint(name, description)
            for rule in _pending:
                if rule.point == name:
                    point._rules.append(rule)
            if point._rules:
                point.armed = True
        return point


def points() -> Dict[str, FaultPoint]:
    """Snapshot of every declared fault point (catalogue lint, tools)."""
    with _points_lock:
        return dict(_points)


def configure(spec: Dict, who: Optional[str] = None, extra_whos=()) -> int:
    """Arm the plane from a parsed spec; returns the number of rules that
    apply to this process. Re-configuring replaces all previous rules
    (``arm_from_env``/``arm_from_store`` layer identity accumulation on
    top so co-hosted components don't strip each other's rules)."""
    global _who
    if who:
        _who = who
    whos = {_who, *extra_whos}
    seed = int(spec.get("seed", os.environ.get("EDL_CHAOS_SEED", 0) or 0))
    rules = [
        _Rule(r, seed, i)
        for i, r in enumerate(spec.get("rules", ()))
    ]
    mine = [r for r in rules if r.applies(whos)]
    with _points_lock:
        _pending.clear()
        for point in _points.values():
            point._rules = []
            point.armed = False
        for rule in mine:
            point = _points.get(rule.point)
            if point is None:
                _pending.append(rule)
            else:
                point._rules.append(rule)
                point.armed = True
    if mine:
        logger.warning(
            "chaos plane armed for %r: %d rule(s) [%s]",
            _who, len(mine),
            ", ".join("%s@%s" % (r.action, r.point) for r in mine),
        )
    return len(mine)


def disarm() -> None:
    _armed_whos.clear()
    configure({"rules": []})


def arm_from_env(who: str, client=None, job_id: str = "") -> int:
    """Arm from the ``EDL_CHAOS`` env contract; 0 rules when unset.

    ``EDL_CHAOS`` is inline JSON, ``@/path/to/spec.json``, or ``store``
    (read the job's ``chaos/spec`` key through ``client``). Call sites are
    the long-lived processes' constructors; with the env unset this is a
    dict lookup and a return.
    """
    raw = os.environ.get("EDL_CHAOS", "").strip()
    if not raw:
        return 0
    try:
        if raw == "store":
            if client is None or not job_id:
                logger.warning(
                    "EDL_CHAOS=store but no store client for %r; disarmed", who
                )
                return 0
            return arm_from_store(client, job_id, who)
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                spec = json.load(f)
        else:
            spec = json.loads(raw)
    except (OSError, ValueError) as exc:
        logger.warning("EDL_CHAOS unusable (%s); plane disarmed", exc)
        return 0
    # accumulate: a launcher embedding a store arms twice ('store', then
    # 'launcher'); both identities must keep matching rules
    _armed_whos.add(who)
    return configure(spec, who, extra_whos=_armed_whos)


def arm_from_store(client, job_id: str, who: str) -> int:
    """Arm from the job's ``chaos/spec`` store key (the ``chaos/``
    keyspace lets a running job be attacked without respawning it)."""
    try:
        value = client.get(chaos_prefix(job_id) + "spec")
    except Exception as exc:  # noqa: BLE001 — chaos must not break startup
        logger.warning("chaos spec read failed: %s", exc)
        return 0
    if not value:
        return 0
    try:
        spec = json.loads(value)
    except ValueError as exc:
        logger.warning("chaos spec in store unparseable: %s", exc)
        return 0
    _armed_whos.add(who)
    return configure(spec, who, extra_whos=_armed_whos)


def publish_spec(client, job_id: str, spec: Dict) -> None:
    """Write a spec into the job's ``chaos/`` keyspace (scenario runner)."""
    client.put(chaos_prefix(job_id) + "spec", json.dumps(spec).encode())
