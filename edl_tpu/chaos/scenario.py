"""Named chaos scenarios: compose faults against a live elastic job and
audit the recovery with :mod:`edl_tpu.chaos.invariants`.

Each scenario owns a :class:`Rig` — a real StoreServer, a ResizeHarness
driving real launcher pods around the chaos trainee, a metrics harvester
scraping every obs endpoint the job registers, and the crash-safe chaos
injection ledger — runs one named fault composition, and returns a
:class:`ScenarioOutcome` whose invariants must ALL hold.

Scenarios (see DESIGN.md "Chaos & fault injection"):

- ``worker-kill``     SIGKILL-equivalent death of a worker mid-step;
- ``store-blip``      the launcher loses the store for longer than the
  lease TTL and must re-register, drain, and restage;
- ``corrupt-ckpt``    machine death + the newest checkpoint version
  corrupted on disk; restore must fall back;
- ``slow-rpc``        a seeded latency tail on every store RPC;
- ``teacher-failover`` a distill teacher dies mid-epoch and a
  replacement joins;
- ``serve-slo-churn`` the serving resilience plane under churn: one
  teacher dies without deregistering (breaker ejection, not
  discovery), one drains gracefully, one grows a sub-SLO latency tail
  (hedges absorb it) — gated on answered-p99 vs SLO, bounded shed,
  breaker-open latency, hedge budget, and zero silent request loss;
- ``store-failover``  the PRIMARY STORE dies mid-job: the warm standby
  promotes within budget, no acked write is lost (strict, semi-sync
  holds the ack until standby-applied), the fenced old primary is
  rejected on restart, watches resume exactly-once;
- ``store-shard-failover`` every primary of a 2-shard control plane
  dies at once: per-shard promotion, per-shard strict zero acked-write
  loss, training completes through it;
- ``ckpt-peer-loss``  the checkpoint-writing pod is SIGKILLed and its
  durable checkpoint tier deleted in the same instant: the survivor and
  the replacement restore from PEER REPLICAS with zero durable-tier
  reads, lost work bounded by the last replicated step;
- ``preempt-drain``   a pod gets an advance preemption notice (SIGTERM):
  emergency checkpoint within budget, DRAINED exit, proactive restage
  with no lease-expiry wait and no grace hold, lost work ≤ one step;
- ``straggler-stall`` a worker wedges mid-step forever: the launcher's
  heartbeat watchdog ejects it within the deadline and the job resumes
  (the matching false-positive drill rides ``slow-rpc``);
- ``monitor-clean``   NO fault at all: the monitor plane's
  zero-false-positive control — a clean run must fire nothing, through
  completion and the post-completion quiet;
- ``autoscale-churn`` the scale plane under a seeded signal trace:
  pool capacity and gradient-noise swings drive real grow/shrink
  decisions through the drain/restage machinery (grow admits held
  pods, shrink publishes autoscale preempt notices), gated on goodput
  loss vs the offline oracle schedule and on decision->restage
  latency;
- ``autoscale-multijob`` two elastic jobs arbitrated on ONE shared
  pool: a higher-priority job is submitted mid-flight, the running job
  is preempted down via the drain plane, the newcomer is gang-released
  only once the freed pods are real, both jobs complete, and neither
  ever publishes a stage below its min world.

Every rig also runs the monitor plane (``edl_tpu/obs/monitor.py``) with
CPU-rig-paced rules; ``worker-kill`` and ``preempt-drain`` additionally
assert that ``goodput-degraded`` fired within a bounded alert latency of
the fault (the ``alerts_fired`` invariant).

All scenarios run under ``JAX_PLATFORMS=cpu`` in tier-1 time budgets and
are deterministic per seed (seeded fault schedules; invariants are
timing-tolerant within explicit budgets).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional

from edl_tpu.chaos import invariants as inv
from edl_tpu.chaos import plane as chaos
from edl_tpu.harness.resize import ResizeHarness
from edl_tpu.store.client import StoreClient
from edl_tpu.store.server import StoreServer
from edl_tpu.utils import telemetry
from edl_tpu.utils.log import get_logger

logger = get_logger("chaos.scenario")

TRAINEE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "trainee.py")

# one downtime budget for every scenario on CPU rigs: generous against
# host-load noise, tight enough to catch a wedged recovery (the real
# numbers land in the outcome's info for trending)
DOWNTIME_BUDGET_S = 45.0

# fault -> monitor "goodput-degraded" firing bound: covers lease expiry,
# the restage gap the rule needs to observe, and the rule's own
# window + for-duration pacing, with CI-noise margin
ALERT_LATENCY_BUDGET_S = 30.0


def _monitor_rules():
    """The built-in rule pack re-paced for CPU-rig time budgets: chaos
    trainees step every ~0.2s and restage gaps last single-digit
    seconds, so detection windows shrink from tens of seconds to ~1s.
    The RULES are the production ones — only the pacing changes."""
    from edl_tpu.obs import monitor as obs_monitor

    rules = obs_monitor.builtin_rules()
    paced = {
        "goodput-degraded": dict(window_s=1.5, for_s=0.75, value=0.05),
        "dead-endpoint": dict(stale_s=4.0),
        "heartbeat-stale": dict(window_s=5.0),
        "straggler-ejections": dict(window_s=10.0),
        "ckpt-restore-fallbacks": dict(window_s=10.0),
        "ckpt-replica-stale": dict(for_s=4.0),
        "telemetry-dropped-keys": dict(window_s=10.0),
        "replication-lag": dict(for_s=2.0),
        "repl-sync-degraded": dict(window_s=10.0),
        "distill-queue-saturated": dict(for_s=2.0),
        # serving plane: chaos drills shed within seconds of an induced
        # overload and trip breakers in under a second
        "serve-shed-rate": dict(window_s=10.0, for_s=2.0),
        "breaker-open": dict(for_s=2.0),
        # numerics plane: chaos trainees publish every 1-2 steps (the
        # drills pin EDL_NUMERICS_EVERY low), so the nonfinite-rate and
        # divergence/stall hold windows shrink with everything else
        "nan-detected": dict(window_s=10.0),
        "loss-spike": dict(window_s=20.0),
        "replica-divergence": dict(for_s=2.0),
        "grad-stall": dict(for_s=4.0),
        # scale plane: the autoscale drills legitimately drain a few
        # times per minute (that IS the scenario), so thrash means a
        # genuine storm — sustained >= 1 autoscale drain per second
        "autoscale-thrash": dict(window_s=10.0, for_s=2.0, value=1.0),
        # memory plane: a chaos OOM must page within the alert budget —
        # the oom counter registers at 0 when the trainee's plane comes
        # up, so the 0 -> 1 jump is always visible to the rate window;
        # pressure holds shrink with the rest of the rig's pacing
        "oom-detected": dict(window_s=10.0),
        "hbm-pressure": dict(for_s=1.0, resolve_s=2.0),
        "donation-dropped": dict(window_s=10.0),
    }
    for rule in rules:
        for field, value in paced.get(rule.name, {}).items():
            setattr(rule, field, value)
    return rules


@dataclasses.dataclass
class ScenarioOutcome:
    name: str
    seed: int
    ok: bool
    invariants: List[inv.InvariantResult]
    info: Dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> Dict:
        return {
            "scenario": self.name,
            "seed": self.seed,
            "ok": self.ok,
            "invariants": [
                {"name": r.name, "ok": r.ok, "detail": r.detail}
                for r in self.invariants
            ],
            "info": self.info,
        }


def _outcome(name: str, seed: int, results: List[inv.InvariantResult], **info) -> ScenarioOutcome:
    return ScenarioOutcome(
        name, seed, all(r.ok for r in results), results, dict(info)
    )


class Rig:
    """One scenario's world: store + harness env + evidence collection.

    ``ha=True`` builds the control plane the store-failover drill
    attacks: a durable primary on a pinned port plus a warm standby
    (synced before the rig is handed out), with every client — the
    rig's own, the launcher's, the trainee's — given the ordered
    two-endpoint list."""

    def __init__(
        self,
        workdir: str,
        job_id: str,
        seed: int,
        ha: bool = False,
        shards: int = 1,
    ) -> None:
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.job_id = job_id
        self.seed = seed
        self.chaos_log = os.path.join(workdir, "chaos.log")
        self.ckpt_dir = os.path.join(workdir, "ckpt")
        # black-box evidence every scenario leaves behind: flight-recorder
        # segments (goodput_accounted audits them; edl-timeline replays
        # the whole run from this workdir) and per-process Chrome traces
        self.flight_dir = os.path.join(workdir, "flight")
        self.trace_dir = os.path.join(workdir, "traces")
        # the env the last harness handed its pods — the knob snapshot
        # archived with the run (the rig's own env carries none of it)
        self.job_env: Dict[str, str] = {}
        self.standby: Optional[StoreServer] = None
        # every (primary, standby) replication group; one entry per shard
        self.shard_servers: List[tuple] = []
        if ha:
            from edl_tpu.utils.net import find_free_ports

            self.primary_dir = os.path.join(workdir, "store-primary")
            # a pinned port so the dead primary can be resurrected at the
            # SAME endpoint every client still lists first
            self.primary_port = find_free_ports(1)[0]
            self.store = StoreServer(
                host="127.0.0.1", port=self.primary_port,
                data_dir=self.primary_dir, name="store-0",
            ).start()
            self.standby = StoreServer(
                host="127.0.0.1", port=0,
                data_dir=os.path.join(workdir, "store-standby"),
                follow=self.store.endpoint, priority=1, failover_grace=1.0,
                name="store-0",
            ).start()
            self.shard_servers.append((self.store, self.standby))
            for i in range(1, max(1, shards)):
                primary_i = StoreServer(
                    host="127.0.0.1", port=0,
                    data_dir=os.path.join(workdir, "store-p%d" % i),
                    name="store-%d" % i,
                ).start()
                standby_i = StoreServer(
                    host="127.0.0.1", port=0,
                    data_dir=os.path.join(workdir, "store-s%d" % i),
                    follow=primary_i.endpoint, priority=1,
                    failover_grace=1.0, name="store-%d" % i,
                ).start()
                self.shard_servers.append((primary_i, standby_i))
            deadline = time.time() + 30
            for _primary, standby_i in self.shard_servers:
                while time.time() < deadline and not standby_i._has_state:
                    time.sleep(0.05)
                assert standby_i._has_state, "standby never bootstrapped"
            if len(self.shard_servers) > 1:
                # the sharded control plane under test: publish the map
                # on the meta shard; every client (the rig's own, the
                # launcher's, the trainee's) discovers it via
                # connect_store and routes by key
                from edl_tpu.store import shard as shard_mod

                boot = StoreClient(self.store.endpoint, timeout=5.0)
                try:
                    shard_mod.publish_shard_map(boot, [
                        [p.endpoint, s.endpoint]
                        for p, s in self.shard_servers
                    ])
                finally:
                    boot.close()
            self.store_endpoints = "%s,%s" % (
                self.store.endpoint, self.standby.endpoint
            )
        else:
            self.store = StoreServer(host="127.0.0.1", port=0).start()
            self.store_endpoints = self.store.endpoint
        from edl_tpu.store.client import connect_store

        self.client = connect_store(self.store_endpoints, timeout=5.0)
        self.harvester = inv.MetricsHarvester(self.client, job_id)
        # the monitor plane rides EVERY scenario: faulted runs prove the
        # alerts fire, the clean control run proves they stay silent
        from edl_tpu.obs import metrics as obs_metrics
        from edl_tpu.obs.monitor import Monitor

        self.monitor_dir = os.path.join(workdir, "monitor")
        self.monitor = Monitor(
            self.store_endpoints,
            job_id,
            rules=_monitor_rules(),
            # a PRIVATE registry: the monitor's self-scrape folds its
            # registry into rule evaluation, and the rig often runs
            # embedded in a long-lived host process (pytest) whose
            # default registry carries state from everything that ran
            # before — e.g. a breaker gauge a PREVIOUS drill's client
            # legitimately left OPEN would fire breaker-open inside the
            # monitor-clean zero-false-positive control. The scenario's
            # real evidence comes from scraping its own workers.
            registry=obs_metrics.MetricsRegistry(),
            # 0.4s matches the harvester's cadence: fast enough for the
            # ~1.5s rule windows, light enough that watching the rig
            # does not load the control plane it watches. HA rigs run
            # the whole primary+standby pair IN-PROCESS, where monitor
            # CPU (scrape parsing, sample persistence) steals GIL time
            # from both event loops and widens the async-replication
            # window the failover drill deliberately attacks — no alert
            # -latency invariant runs there, so watch at a gentle 1s
            interval=1.0 if ha else 0.4,
            # telemetry.collect() is three keyspace range scans decoded
            # in-process: skip it where the pair shares the GIL
            collect_telemetry=not ha,
            retention_s=60.0,
            monitor_dir=self.monitor_dir,
        ).start()

    def harness(
        self,
        spec: Optional[Dict],
        nodes_range: str = "1:2",
        ttl: float = 0.8,
        total: int = 16,
        ckpt_every: int = 4,
        step_time: float = 0.08,
        nproc: int = 1,
        extra: Optional[Dict[str, str]] = None,
    ) -> ResizeHarness:
        env = {
            "EDL_CHAOS_LOG": self.chaos_log,
            "EDL_CHAOS_SEED": str(self.seed),
            "EDL_CKPT_PATH": self.ckpt_dir,
            "EDL_FLIGHT_DIR": self.flight_dir,
            "EDL_TRACE_DIR": self.trace_dir,
            # the scenario-level archive (run_scenario) is the only one:
            # the harness's own EDL_RUN_ARCHIVE hook must not produce a
            # second, invariant-less bundle of the same run
            "EDL_RUN_ARCHIVE": "0",
            "EDL_OBS_PORT": "0",
            "JAX_PLATFORMS": "cpu",
            "EDL_DEVICES_PER_PROC": "1",
            "EDL_CHAOS_TOTAL_STEPS": str(total),
            "EDL_CHAOS_CKPT_EVERY": str(ckpt_every),
            "EDL_CHAOS_STEP_TIME": str(step_time),
        }
        if self.standby is not None:
            # HA rigs: the cache exchange's manifest puts are journal
            # traffic riding the primary->standby replication stream —
            # exactly the async window the failover drill kills into
            # (same reasoning as the gentle monitor pacing above; the
            # exchange has its own e2e drills in tests/test_aot.py)
            env["EDL_CACHE_EXCHANGE"] = "0"
        if spec is not None:
            env["EDL_CHAOS"] = json.dumps(spec)
        if extra:
            env.update(extra)
        self.job_env = dict(env)
        return ResizeHarness(
            self.store_endpoints,
            self.job_id,
            TRAINEE,
            nodes_range=nodes_range,
            ttl=ttl,
            log_dir=os.path.join(self.workdir, "logs"),
            extra_env=env,
        )

    # -- observation -------------------------------------------------------

    def cursor(self, rank: int = 0) -> int:
        try:
            value = self.client.get(
                chaos.chaos_prefix(self.job_id) + "progress/step.w%d" % rank
            )
        except Exception:  # noqa: BLE001 — store may be mid-fault
            return -1
        return int(value) if value else -1

    def wait_cursor(self, min_step: int, timeout: float) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.cursor() >= min_step:
                return True
            time.sleep(0.1)
        return False

    def evidence(self) -> inv.Evidence:
        return inv.Evidence(
            progress=inv.read_progress(self.client, self.job_id),
            telemetry=telemetry.collect(self.client, self.job_id),
            chaos_log=inv.read_chaos_log(self.chaos_log),
            metrics=self.harvester.snapshot(),
        )

    def flight_events(self) -> list:
        """Merged flight-recorder events from every process of the run
        (killed ones included — that is the point of the recorder)."""
        from edl_tpu.obs import events as obs_events

        return obs_events.read_segments(self.flight_dir)

    def trace_spans(self) -> list:
        """Linked spans from every process's trace export (the
        distributed-tracing evidence critical_path_traced audits)."""
        from edl_tpu.obs import tracepath

        return tracepath.load_run(self.trace_dir)

    def alerts(self) -> dict:
        """The monitor plane's published alert records for this job."""
        from edl_tpu.obs.monitor import read_alerts

        return read_alerts(self.client, self.job_id)

    def close(self) -> None:
        self.monitor.stop()
        self.harvester.stop()
        self.client.close()
        self.store.stop()
        if self.standby is not None:
            self.standby.stop()
        for primary, standby in self.shard_servers[1:]:
            primary.stop()
            standby.stop()


# -- scenarios ----------------------------------------------------------------


def worker_kill(rig: Rig) -> ScenarioOutcome:
    """A worker dies SIGKILL-style mid-step. Its pod leaves the job, the
    survivor drains on lease expiry, restages at the smaller world, and
    resumes from the shared checkpoint."""
    total, ckpt_every = 30, 3
    spec = {
        "seed": rig.seed,
        "rules": [
            # the 7th step fired by whichever process runs global rank 1
            # (step 6): late enough that rank 0's first checkpoint —
            # save(3) blocks its loop at the step-2/3 boundary — is
            # provably durable before the fault, so "resumed, not
            # restarted" is a deterministic property, not a race against
            # how fast the survivor drains
            {"point": "train.step", "proc": "worker", "action": "kill",
             "match": {"rank": "1"}, "after": 7},
        ],
    }
    # steps slow enough that the survivor cannot finish before the kill,
    # grace window, lease expiry, and restage all play out mid-training
    harness = rig.harness(
        spec, nodes_range="1:2", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
    )
    try:
        done = harness.run_schedule([2], interval=3.0, timeout=150.0)
    finally:
        harness.shutdown()
    ev = rig.evidence()
    alerts = rig.alerts()
    kills = [
        e for e in ev.chaos_log
        if e.get("point") == "train.step" and e.get("action") == "kill"
    ]
    prefault = max(
        (int(e["ctx"].get("step", 0)) for e in kills), default=None
    )
    kill_ts = min((float(e.get("ts", 0.0)) for e in kills), default=0.0)
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.resumed_past_prefault_step(ev, prefault),
        inv.replay_bounded(ev, ckpt_every),
        inv.downtime_bounded(ev, DOWNTIME_BUDGET_S),
        inv.fault_injected(ev, "train.step", "kill"),
        inv.multiple_stages(ev),
        # the accounting itself is under test: the SIGKILLed rank's
        # segments must still add up (flight recorder survives the kill)
        inv.goodput_accounted(rig.flight_events()),
        # so is the tracing plane: the post-kill restage must stitch
        # into one cross-process critical path that agrees with the
        # goodput ledger's restage lane
        inv.critical_path_traced(rig.trace_spans(), rig.flight_events()),
        # the monitor plane is under test too: the kill must be noticed
        # within the alert-latency budget — dead-endpoint detects the
        # SIGKILLed worker structurally; goodput-degraded joins when
        # the restage gap is long enough for the paced rate window
        # (the sharded control plane shortened that gap to ~2 s on this
        # rig, below the window — recovery outrunning detection)
        inv.alert_fired_any(
            alerts, ["goodput-degraded", "dead-endpoint"],
            kill_ts, ALERT_LATENCY_BUDGET_S,
        ),
        # the resize continuity sentinel: every post-kill restore must
        # have re-verified the checkpoint's numerics fingerprint and
        # found the resumed loss continuous with the saved one
        inv.numerics_continuous(rig.flight_events()),
    ]
    return _outcome(
        "worker-kill", rig.seed, results,
        harness_completed=done, prefault_step=prefault,
        alerts_fired=sorted(alerts),
    )


def _consistency_results(
    rig: Rig, *churns
) -> List[inv.InvariantResult]:
    """Stop the consistency probes, replay the op tape through the
    history checker, drop the verdict into the flight dir (edl-timeline
    instant + archive evidence), and return the three consistency
    invariants every store drill must hold."""
    from edl_tpu.chaos import consistency as cons

    for churn in churns:
        churn.stop()
    report = cons.check_history(rig.flight_events())
    cons.record_verdict(report, rig.flight_dir)
    return [
        inv.no_stale_reads(report),
        inv.monotonic_session_reads(report),
        inv.watch_gap_free(report),
    ]


def store_blip(rig: Rig) -> ScenarioOutcome:
    """The launcher's store connection blips for longer than the lease
    TTL: leases expire, the shared retry path (utils/retry.py)
    re-registers, the job drains and restages, training resumes."""
    from edl_tpu.chaos.consistency import ConsistencyChurn

    total, ckpt_every = 24, 3
    spec = {
        "seed": rig.seed,
        "rules": [
            # after 60 launcher requests (a few seconds into TRAINING at
            # the coalesced-renew request rate: ~30 land during
            # bootstrap), partition the store for 3 s of wall clock —
            # comfortably past the 0.8 s TTL. (A drop COUNT stopped
            # being a time proxy when lease renewals got coalesced into
            # one batched RPC per tick: the old "drop the next 35"
            # spanned ~10x the wall time at the reduced QPS, and
            # request #30 moved from mid-training into bootstrap.)
            {"point": "store.client.request", "proc": "launcher",
             "action": "partition", "after": 60, "duration_s": 3.0},
        ],
    }
    harness = rig.harness(
        spec, nodes_range="1:1", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
    )
    # the consistency probe churns taped reads/writes/watches through
    # the whole blip: the history checker proves the degraded window
    # never showed anyone a stale or rewound view
    churn = ConsistencyChurn(rig.store_endpoints, rig.flight_dir)
    try:
        done = harness.run_schedule([1], interval=3.0, timeout=150.0)
    finally:
        harness.shutdown()
    consistency_results = _consistency_results(rig, churn)
    ev = rig.evidence()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        inv.fault_injected(ev, "store.client.request", "partition", at_least=5),
        inv.retries_observed(ev),
        inv.downtime_bounded(ev, DOWNTIME_BUDGET_S),
        *consistency_results,
    ]
    return _outcome("store-blip", rig.seed, results, harness_completed=done)


def corrupt_checkpoint(rig: Rig) -> ScenarioOutcome:
    """Machine death plus a corrupted newest checkpoint: the replacement
    pod's restore must fall back past the torn version and resume from
    the previous good one."""
    total, ckpt_every = 18, 4
    harness = rig.harness(
        None, nodes_range="1:1", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.15,
    )
    corrupted_step = None
    try:
        harness.start_pod()
        # let >= 2 versions land (saves at steps 4 and 8), then "lose the
        # machine" mid-flight and tear the newest version on disk
        assert rig.wait_cursor(2 * ckpt_every, timeout=90.0), (
            "trainee never reached step %d (cursor %d)"
            % (2 * ckpt_every, rig.cursor())
        )
        if harness.pods:
            harness.kill_pod(harness.pods[-1])
        corrupted_step = corrupt_latest_checkpoint(rig.ckpt_dir)
        harness.start_pod()
        done = harness.run_schedule([], interval=1.0, timeout=120.0)
    finally:
        harness.shutdown()
    ev = rig.evidence()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.checkpoint_fell_back(ev, corrupted_step or 0),
        inv.resumed_past_prefault_step(ev, corrupted_step),
        inv.downtime_bounded(ev, DOWNTIME_BUDGET_S),
    ]
    return _outcome(
        "corrupt-ckpt", rig.seed, results,
        harness_completed=done, corrupted_step=corrupted_step,
    )


def slow_rpc(rig: Rig) -> ScenarioOutcome:
    """A seeded latency tail on every store RPC server-side: the job must
    complete in one generation — slow control plane, no spurious drains,
    and (the watchdog false-positive drill) ZERO straggler ejections even
    with the stall deadline tightened far below production defaults."""
    total, ckpt_every = 16, 4
    # the store runs in THIS process: arm the plane directly
    armed = chaos.configure(
        {
            "seed": rig.seed,
            "rules": [
                {"point": "store.server.dispatch", "proc": "store",
                 "action": "delay", "delay_s": 0.04, "prob": 0.25,
                 "times": 0},
            ],
        },
        who="store",
    )
    harness = rig.harness(
        None, nodes_range="1:1", ttl=2.5, total=total, ckpt_every=ckpt_every,
        extra={
            # the drill: heartbeats flowing, watchdog armed TIGHT — slow
            # store RPCs must still not look like a wedged worker
            "EDL_HEARTBEAT_EVERY": "0.05",
            "EDL_STALL_DEADLINE": "8.0",
            "EDL_STALL_FLOOR": "2.0",
        },
    )
    try:
        done = harness.run_schedule([1], interval=3.0, timeout=120.0)
        # evidence BEFORE shutdown: the shutdown SIGTERM is itself a drain
        # notice now, and its preempt bookkeeping must not pollute the
        # zero-stragglers ledger of the run under test
        ev = rig.evidence()
    finally:
        harness.shutdown()
        chaos.disarm()
    from edl_tpu.obs import metrics as obs_metrics

    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.single_stage(ev),
        inv.zero_stragglers(ev),
        inv.faults_visible_in_metrics(
            ev, "store.server.dispatch",
            extra_registry=obs_metrics.default_registry(),
        ),
    ]
    return _outcome(
        "slow-rpc", rig.seed, results,
        harness_completed=done, rules_armed=armed,
    )


def teacher_failover(rig: Rig) -> ScenarioOutcome:
    """A distill teacher dies mid-epoch; the reader's pool cools it down,
    re-queues its in-flight tasks, and finishes the epoch on the
    replacement — every batch exactly once, in order."""
    import numpy as np

    from edl_tpu.distill.discovery import DiscoveryClient, DiscoveryService, TeacherRegister
    from edl_tpu.distill.reader import DistillReader
    from edl_tpu.distill.serving import EchoPredictBackend, PredictServer

    # slow each predict a little so the failover lands mid-epoch
    chaos.configure(
        {
            "seed": rig.seed,
            "rules": [
                {"point": "distill.predict", "proc": "student",
                 "action": "delay", "delay_s": 0.03, "times": 0},
            ],
        },
        who="student",
    )
    job = rig.job_id
    num_batches, batch = 24, 8
    t1 = PredictServer(EchoPredictBackend()).start()
    t2 = PredictServer(EchoPredictBackend()).start()
    svc = DiscoveryService(rig.store.endpoint, job, ["teacher"])
    reg1 = TeacherRegister(rig.store.endpoint, job, "teacher", t1.endpoint)
    reg2 = TeacherRegister(rig.store.endpoint, job, "teacher", t2.endpoint)
    probe = DiscoveryClient(
        rig.store.endpoint, job, "teacher", client_id="chaos-probe"
    )
    replacement = []

    def batches():
        for i in range(num_batches):
            x = np.full((batch, 4), float(i), np.float32)
            yield (x,)

    reader = DistillReader(feeds=("x",), teacher_batch_size=batch, require_num=2)
    reader.set_dynamic_teacher(rig.store.endpoint, job, "teacher")
    reader.set_batch_generator(batches)
    seen: List[int] = []
    try:
        probe.wait_servers(timeout=10.0)
        for i, out in enumerate(reader()):
            seen.append(int(out[0][0][0]))
            if i == 4:
                # teacher 1 dies mid-epoch (socket resets, not a clean bye)
                reg1.stop()
                t1.stop()
            if i == 8 and not replacement:
                srv = PredictServer(EchoPredictBackend()).start()
                replacement.append(
                    (srv, TeacherRegister(rig.store.endpoint, job, "teacher", srv.endpoint))
                )
    finally:
        reader.stop()
        probe.stop()
        for srv, reg in replacement:
            reg.stop()
            srv.stop()
        reg2.stop()
        svc.stop()
        t2.stop()
        chaos.disarm()
    from edl_tpu.obs import metrics as obs_metrics

    ordered = seen == list(range(num_batches))
    results = [
        inv.InvariantResult(
            "batches_exactly_once_in_order",
            ordered,
            "yielded %d/%d%s" % (
                len(seen), num_batches,
                "" if ordered else (", got %s" % seen[:30]),
            ),
        ),
        inv.faults_visible_in_metrics(
            inv.Evidence(), "distill.predict",
            extra_registry=obs_metrics.default_registry(),
        ),
    ]
    return _outcome(
        "teacher-failover", rig.seed, results, batches=len(seen),
    )


BREAKER_OPEN_BUDGET_S = 5.0   # teacher death -> breaker OPEN bound
DRAIN_GRACE_S = 2.0           # drain mark -> assignment propagation bound


def serve_slo_churn(rig: Rig) -> ScenarioOutcome:
    """The serving resilience plane under teacher churn, gated on SLO.

    A 4-teacher fleet serves paced predict load through the full stack —
    admission control on the teachers, breaker/hedge/retry-budget routing
    in the :class:`~edl_tpu.distill.slo.SloDriver` — while three distinct
    faults land mid-run:

    - one teacher **dies without deregistering** (its store lease keeps
      advertising the corpse for the rest of the run — the circuit
      breaker, not discovery, must take it out of rotation);
    - one teacher **drains gracefully** (the balancer must stop routing
      new work to it within a propagation grace);
    - one teacher grows a **latency tail** (a chaos delay below the SLO
      — hedges and queue-weighted routing must absorb it, not shed it).

    GREEN means: every issued request got exactly one explicit verdict
    (nothing silently lost), the answered-request p99 stayed under the
    SLO, the shed fraction stayed bounded, the breaker opened on the
    dead teacher within budget, and hedging stayed inside its
    fraction-of-primaries construction."""
    import threading

    import numpy as np

    from edl_tpu.distill.discovery import (
        DiscoveryClient,
        DiscoveryService,
        TeacherRegister,
    )
    from edl_tpu.distill.resilience import BreakerBoard
    from edl_tpu.distill.serving import EchoPredictBackend, PredictServer
    from edl_tpu.distill.slo import SloDriver

    job = rig.job_id
    slo_ms = 400.0
    qps, duration = 25.0, 12.0
    teachers = [
        PredictServer(EchoPredictBackend(), queue_limit=32, slo_ms=slo_ms).start()
        for _ in range(4)
    ]
    dead, drained, slowed = (
        teachers[0].endpoint, teachers[1].endpoint, teachers[2].endpoint,
    )
    svc = DiscoveryService(rig.store.endpoint, job, ["teacher"])
    regs = [
        TeacherRegister(rig.store.endpoint, job, "teacher", t.endpoint)
        for t in teachers
    ]
    probe = DiscoveryClient(
        rig.store.endpoint, job, "teacher", client_id="slo-driver"
    )

    opened_at: Dict[str, float] = {}
    breakers = BreakerBoard(
        failures=3, open_s=2.0,
        on_open=lambda e: opened_at.setdefault(e, time.monotonic()),
    )
    data = np.random.default_rng(rig.seed).random((4, 8), dtype=np.float32)
    driver = SloDriver(
        lambda: probe.get_servers()[1],
        lambda seq: {"x": data},
        qps=qps,
        duration_s=duration,
        slo_ms=slo_ms,
        concurrency=6,
        rpc_timeout=2.0,
        seed=rig.seed,
        breakers=breakers,
    )
    box: Dict = {}

    def _run() -> None:
        box["summary"] = driver.run()

    t_kill = None
    t_drain_off = None
    try:
        probe.wait_servers(timeout=10.0)
        th = threading.Thread(target=_run, name="slo-churn", daemon=True)
        start = time.monotonic()
        th.start()
        # t+3s: teacher 0 dies WITHOUT a goodbye — its registration lease
        # outlives it, so discovery keeps offering the corpse and only
        # the breaker can eject it
        time.sleep(max(0.0, start + 3.0 - time.monotonic()))
        teachers[0].stop()
        t_kill = time.monotonic()
        # t+5s: teacher 1 drains gracefully (balancer-side ejection)
        time.sleep(max(0.0, start + 5.0 - time.monotonic()))
        regs[1].drain()
        t_drain_off = time.monotonic() - start
        # t+6.5s: teacher 2 grows a 250 ms tail — UNDER the 400 ms SLO,
        # so the right response is hedges + steering, not shedding
        time.sleep(max(0.0, start + 6.5 - time.monotonic()))
        chaos.configure(
            {
                "seed": rig.seed,
                "rules": [
                    {"point": "distill.serving.predict", "action": "delay",
                     "delay_s": 0.25, "times": 0,
                     "match": {"port": str(teachers[2].port)}},
                ],
            },
            who="slo-churn",
        )
        th.join(timeout=duration + 45.0)
        driver_done = not th.is_alive()
    finally:
        chaos.disarm()
        probe.stop()
        for reg in regs:
            try:
                reg.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        svc.stop()
        for t in teachers[1:]:
            t.stop()
    from edl_tpu.obs import metrics as obs_metrics

    summary = box.get("summary") or {}
    counts = summary.get("verdicts", {})
    requests = summary.get("requests", 0)
    total = int(round(qps * duration))
    p99 = summary.get("serve_p99_ms")
    shed_pct = summary.get("serve_shed_pct", 100.0)
    hedges = summary.get("hedges", 0)
    open_lat = (
        opened_at[dead] - t_kill
        if (dead in opened_at and t_kill is not None) else None
    )
    late_to_drained = [
        v for v in driver.verdicts
        if v.endpoint == drained
        and t_drain_off is not None
        and v.t_s > t_drain_off + DRAIN_GRACE_S
    ]
    results = [
        inv.InvariantResult(
            "every_request_has_a_verdict",
            driver_done and requests == total
            and sum(counts.values()) == total,
            "driver %s; %d/%d verdicts: %s" % (
                "finished" if driver_done else "WEDGED",
                sum(counts.values()), total, counts,
            ),
        ),
        inv.InvariantResult(
            "answered_p99_within_slo",
            p99 is not None and p99 <= slo_ms,
            "p99 %s ms vs SLO %.0f ms (ok=%d late=%d)" % (
                p99, slo_ms, counts.get("ok", 0), counts.get("late", 0),
            ),
        ),
        inv.InvariantResult(
            "shed_fraction_bounded",
            shed_pct <= 25.0,
            "shed %.2f%% (bound 25%%)" % shed_pct,
        ),
        inv.InvariantResult(
            "breaker_opened_on_dead_teacher",
            open_lat is not None and open_lat <= BREAKER_OPEN_BUDGET_S,
            "open after %s (budget %.0fs); opened: %s" % (
                "%.2fs" % open_lat if open_lat is not None else "NEVER",
                BREAKER_OPEN_BUDGET_S, sorted(opened_at),
            ),
        ),
        inv.InvariantResult(
            "hedges_within_budget",
            hedges <= 0.10 * max(1, requests) + 5.0 + 1e-9,
            "%d hedges vs 0.10 x %d primaries + 5 burst" % (hedges, requests),
        ),
        inv.InvariantResult(
            "drained_teacher_left_rotation",
            not late_to_drained,
            "%d primaries routed to the drained teacher > %.1fs after "
            "its drain mark" % (len(late_to_drained), DRAIN_GRACE_S),
        ),
        inv.faults_visible_in_metrics(
            inv.Evidence(), "distill.serving.predict",
            extra_registry=obs_metrics.default_registry(),
        ),
    ]
    return _outcome(
        "serve-slo-churn", rig.seed, results,
        breaker_open_s=round(open_lat, 2) if open_lat is not None else None,
        verdicts=counts,
        hedge_wins=summary.get("hedge_wins"),
        retries_spent=summary.get("retries_spent"),
        rollups={
            "serve_qps": summary.get("serve_qps"),
            "serve_p99_ms": p99,
            "serve_shed_pct": shed_pct,
        },
    )


DRAIN_BUDGET_S = 6.0       # notice -> emergency ckpt + DRAINED_EXIT bound
STALL_EJECT_BUDGET_S = 8.0  # wedge injection -> watchdog ejection bound


def _published_stage_count(rig: Rig) -> int:
    try:
        data = telemetry.collect(rig.client, rig.job_id)
    except Exception:  # noqa: BLE001 — store may be mid-churn
        return 0
    return sum(
        1 for evs in data.get("events", {}).values() if "published" in evs
    )


def preempt_drain(rig: Rig) -> ScenarioOutcome:
    """A pod receives an advance preemption notice (SIGTERM — a spot-VM
    reclaim / k8s eviction) mid-training. Its workers must take an
    emergency checkpoint inside the drain budget and exit DRAINED; the
    survivor must restage PROACTIVELY — excluded-by-notice, not by lease
    expiry, with no failure-grace hold — and resume from the emergency
    checkpoint, losing at most the one in-flight step."""
    total, ckpt_every = 24, 4
    # ttl deliberately HIGH: any reliance on lease expiry (the reactive
    # path this scenario outlaws) would blow the proactive-drain bound
    harness = rig.harness(
        None, nodes_range="1:2", ttl=5.0, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
        extra={
            "EDL_HEARTBEAT_EVERY": "0.05",
            "EDL_DRAIN_BUDGET": str(DRAIN_BUDGET_S),
        },
    )
    import signal as _signal

    drained_rc = None
    drain_exit_s = None
    cursor_at_notice = -1
    try:
        # pod A alone first: it deterministically wins rank slot 0 (and
        # with it the checkpoint-writing rank and the leadership)
        harness.start_pod()
        assert rig.wait_cursor(2, timeout=90.0), (
            "first pod never started stepping (cursor %d)" % rig.cursor()
        )
        harness.start_pod()  # pod B joins; the job restages to world 2
        deadline = time.time() + 60
        while time.time() < deadline and _published_stage_count(rig) < 2:
            time.sleep(0.2)
        assert _published_stage_count(rig) >= 2, "world-2 stage never published"
        floor = rig.cursor() + 2
        assert rig.wait_cursor(floor, timeout=60.0), (
            "world-2 stage never stepped (cursor %d)" % rig.cursor()
        )
        # the notice: SIGTERM pod A (rank 0, the leader, the ckpt writer)
        cursor_at_notice = rig.cursor()
        victim = harness.pods[0]
        t0 = time.monotonic()
        notice_ts = time.time()
        victim.send_signal(_signal.SIGTERM)
        drained_rc = victim.wait()
        drain_exit_s = time.monotonic() - t0
        harness.pods.remove(victim)
        # pod B: sees preempt/A, leads (draining pods don't), republishes
        # world-1 WITHOUT waiting for A's lease, restores the emergency
        # checkpoint, finishes the job
        done = harness.run_schedule([], interval=1.0, timeout=150.0)
        ev = rig.evidence()
        alerts = rig.alerts()
    finally:
        harness.shutdown()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        inv.drained_before_deadline(ev, DRAIN_BUDGET_S),
        inv.proactive_drain(ev, 2.5),
        inv.lost_work_bounded(ev, cursor_at_notice),
        inv.drained_exit_clean(drained_rc, drain_exit_s, DRAIN_BUDGET_S + 3.0),
        inv.downtime_bounded(ev, DOWNTIME_BUDGET_S),
        inv.multiple_stages(ev, at_least=3),
        inv.goodput_accounted(rig.flight_events()),
        # the drain-triggered restage must stitch into one cross-process
        # critical path that agrees with the goodput restage lane
        inv.critical_path_traced(rig.trace_spans(), rig.flight_events()),
        # the monitor plane must notice the drain: restart-detected /
        # dead-endpoint fire structurally on the drained pod's exit and
        # the survivor's respawn; goodput-degraded joins when the
        # (proactively shortened) gap outlasts the paced rate window
        inv.alert_fired_any(
            alerts,
            ["goodput-degraded", "restart-detected", "dead-endpoint"],
            notice_ts, ALERT_LATENCY_BUDGET_S,
        ),
        # the survivor resumed from the EMERGENCY checkpoint — the
        # continuity sentinel proves it resumed the same training run
        # (fingerprint verified, loss continuous), not a silent restart
        inv.numerics_continuous(rig.flight_events()),
    ]
    return _outcome(
        "preempt-drain", rig.seed, results,
        harness_completed=done, cursor_at_notice=cursor_at_notice,
        drained_rc=drained_rc, drain_exit_s=round(drain_exit_s or -1, 2),
        alerts_fired=sorted(alerts),
    )


def ckpt_peer_loss(rig: Rig) -> ScenarioOutcome:
    """The checkpoint-writing pod DIES (SIGKILL) and its durable
    checkpoint tier is DELETED in the same instant — the
    one-slow-or-dead-filesystem failure the peer-replication plane
    exists to survive. The job runs with a pod-local checkpoint tier
    (``EDL_CKPT_LOCAL_BASE``) and K=1 ring-successor replication: every
    save lands locally, is pushed to the surviving pod's replica holder,
    and mirrors to the durable dir in the background. After the fault,
    the survivor and the replacement pod must restore from PEER REPLICAS
    with zero durable-tier reads (tier-labeled restore metrics + flight
    records), lose no more work than the last replicated step, and the
    restore hop must be visible as a ``ckpt_restore`` segment on the
    edl-trace restage critical path."""
    import shutil

    from edl_tpu.checkpoint import replicate as ckpt_replicate
    from edl_tpu.cluster.contract import RANK_SERVICE

    total, ckpt_every = 24, 3
    local_base = os.path.join(rig.workdir, "ckpt-local")
    harness = rig.harness(
        None, nodes_range="1:2", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
        extra={
            "EDL_CKPT_LOCAL_BASE": local_base,
            "EDL_CKPT_REPLICAS": "1",
        },
    )
    replicated_step = None
    kill_ts = 0.0
    try:
        # pod A alone first: it deterministically wins rank slot 0 (the
        # checkpoint-writing rank and the leadership)
        harness.start_pod()
        assert rig.wait_cursor(2, timeout=90.0), (
            "first pod never started stepping (cursor %d)" % rig.cursor()
        )
        harness.start_pod()  # pod B joins; its launcher holds A's replicas
        deadline = time.time() + 60
        while time.time() < deadline and _published_stage_count(rig) < 2:
            time.sleep(0.2)
        assert _published_stage_count(rig) >= 2, "world-2 stage never published"
        # wait until a world-2 checkpoint is saved AND fully replicated
        # to the peer holder (the manifest is the proof)
        deadline = time.time() + 90
        while time.time() < deadline:
            newest = ckpt_replicate.newest_replicated_step(
                rig.client, rig.job_id
            )
            if newest is not None and newest >= 2 * ckpt_every:
                replicated_step = newest
                break
            time.sleep(0.2)
        assert replicated_step is not None, (
            "no complete peer replica of a world-2 checkpoint within 90s"
        )
        # the fault: SIGKILL pod A (saver/leader), wipe its machine-local
        # state, AND delete the durable tier — recovery may read peers only
        slot0 = rig.client.get("/%s/%s/0" % (rig.job_id, RANK_SERVICE))
        victim_pod = slot0.decode() if slot0 else ""
        kill_ts = time.time()
        harness.kill_pod(harness.pods[0])
        shutil.rmtree(rig.ckpt_dir, ignore_errors=True)
        if victim_pod:
            shutil.rmtree(
                os.path.join(local_base, victim_pod), ignore_errors=True
            )
            shutil.rmtree(
                os.path.join(local_base, victim_pod + ".replicas"),
                ignore_errors=True,
            )
        harness.start_pod()  # the replacement: empty local tier, no durable
        done = harness.run_schedule([], interval=1.0, timeout=150.0)
        ev = rig.evidence()
    finally:
        harness.shutdown()
    flights = rig.flight_events()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        # lost work bounded by the LAST REPLICATED step: the post-fault
        # restore must land exactly there (slack 0 — the replica IS the
        # recovery point, unlike a drain's one in-flight step)
        inv.lost_work_bounded(ev, replicated_step or 0, slack_steps=0),
        inv.resumed_past_prefault_step(ev, replicated_step),
        inv.peer_tier_restored(ev, flights, kill_ts),
        inv.restore_segment_traced(rig.trace_spans()),
        inv.downtime_bounded(ev, DOWNTIME_BUDGET_S),
        inv.multiple_stages(ev, at_least=3),
        inv.goodput_accounted(flights),
        inv.critical_path_traced(rig.trace_spans(), flights),
    ]
    return _outcome(
        "ckpt-peer-loss", rig.seed, results,
        harness_completed=done, replicated_step=replicated_step,
        victim=victim_pod[:8] if victim_pod else "?",
    )


def straggler_stall(rig: Rig) -> ScenarioOutcome:
    """A worker wedges inside a 'collective' (a 120 s chaos delay at one
    rank's step 5 — far past any step time). Without the watchdog this
    hangs the job forever without tripping ANY failure path: the process
    is alive, its lease renews, nothing exits. The launcher-side watchdog
    must spot the silent heartbeat (behind its peer, quiet past the
    peer-median deadline), eject the wedge via kill + drain, and the
    restaged generation must resume from the checkpoint and finish."""
    total, ckpt_every = 40, 4
    spec = {
        "seed": rig.seed,
        "rules": [
            # min_nodes=2 (below) pins rank 1 to start at step 0, so the
            # wedge fires exactly once: after the ejection the restage
            # resumes from a checkpoint far past step 5
            {"point": "train.step", "proc": "worker", "action": "delay",
             "delay_s": 120.0, "match": {"rank": "1", "step": "5"}},
        ],
    }
    harness = rig.harness(
        spec, nodes_range="2:2", ttl=1.5, total=total,
        ckpt_every=ckpt_every, step_time=0.15,
        extra={
            "EDL_HEARTBEAT_EVERY": "0.05",
            "EDL_STALL_FLOOR": "2.0",
        },
    )
    try:
        done = harness.run_schedule([2], interval=3.0, timeout=150.0)
        ev = rig.evidence()
    finally:
        harness.shutdown()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        inv.fault_injected(ev, "train.step", "delay"),
        inv.straggler_ejected_within(ev, STALL_EJECT_BUDGET_S),
        inv.metric_advanced(ev, "edl_launch_straggler_ejections_total"),
        inv.multiple_stages(ev, at_least=2),
    ]
    return _outcome("straggler-stall", rig.seed, results, harness_completed=done)


def monitor_clean(rig: Rig) -> ScenarioOutcome:
    """NO fault at all — the monitor plane's zero-false-positive
    control. A clean single-pod run, through completion AND a
    post-completion quiet window (a finished job going silent must read
    as done, not degraded — the monitor suppresses on the COMPLETE
    status key), must publish not a single alert."""
    total, ckpt_every = 20, 5
    harness = rig.harness(
        None, nodes_range="1:1", ttl=2.0, total=total,
        ckpt_every=ckpt_every, step_time=0.1,
    )
    try:
        done = harness.run_schedule([1], interval=0.5, timeout=120.0)
        # the teeth: keep the monitor evaluating PAST completion — the
        # job going quiet here is exactly the false positive this
        # scenario outlaws
        time.sleep(1.5)
        alerts = rig.alerts()
        ev = rig.evidence()
    finally:
        harness.shutdown()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.single_stage(ev),
        inv.zero_stragglers(ev),
        inv.no_false_alerts(alerts),
    ]
    return _outcome(
        "monitor-clean", rig.seed, results,
        harness_completed=done, monitor_health=rig.monitor.health(),
    )


def grad_corrupt(rig: Rig) -> ScenarioOutcome:
    """Silent numerics corruption — the red drill for the numerics
    plane. One rank's gradient bytes are flipped mid-training (a DMA
    bit-flip / faulty host, the failure SDC postmortems describe): the
    training loop itself keeps stepping happily, so only the fused
    numerics probe can see it. The corrupted update blows the params
    out of float32 range, the next loss overflows to inf, and the plane
    must turn that into evidence end-to-end: a ``nonfinite`` flight
    record, the ``edl_train_nonfinite_total`` counter jump, and a
    ``nan-detected`` (or ``loss-spike``) alert inside the latency
    budget."""
    total, ckpt_every = 40, 5
    spec = {
        "seed": rig.seed,
        "rules": [
            # the 17th gradient rank 0 computes: deep enough into
            # training that the loss-spike rule's z-score history and
            # the nan-detected rate window are both primed with clean
            # samples before the poison lands
            {"point": "train.grad.corrupt", "proc": "worker",
             "action": "corrupt", "match": {"rank": "0"}, "after": 16,
             "times": 1},
        ],
    }
    # publish every 2 steps: the drill audits detection LATENCY, so the
    # probe cadence (not the monitor's) must not dominate the budget
    harness = rig.harness(
        spec, nodes_range="1:2", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.25,
        extra={"EDL_NUMERICS_EVERY": "2"},
    )
    try:
        done = harness.run_schedule([2], interval=3.0, timeout=180.0)
    finally:
        harness.shutdown()
    ev = rig.evidence()
    alerts = rig.alerts()
    corrupts = [
        e for e in ev.chaos_log
        if e.get("point") == "train.grad.corrupt"
        and e.get("action") == "corrupt"
    ]
    corrupt_ts = min(
        (float(e.get("ts", 0.0)) for e in corrupts), default=0.0
    )
    results = [
        # the job must FINISH — corruption detection is observability,
        # not a crash: the run completes and the evidence convicts it
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.fault_injected(ev, "train.grad.corrupt", "corrupt"),
        # the probe's own black-box record of the blowup (survives any
        # later process death, feeds edl-timeline's overlay)
        inv.nonfinite_recorded(rig.flight_events()),
        # the tripwires: nan-detected on the counter jump is the
        # structural detector; loss-spike's z-score joins when the inf
        # loss lands in a primed history window
        inv.alert_fired_any(
            alerts, ["nan-detected", "loss-spike"],
            corrupt_ts, ALERT_LATENCY_BUDGET_S,
        ),
    ]
    return _outcome(
        "grad-corrupt", rig.seed, results,
        harness_completed=done, corrupt_ts=corrupt_ts,
        alerts_fired=sorted(alerts),
    )


def hbm_oom(rig: Rig) -> ScenarioOutcome:
    """Device OOM mid-training — the red drill for the memory plane.
    Rank 0's step dispatch hits RESOURCE_EXHAUSTED (the ``train.mem.oom``
    drop fault, re-raised at the fire site as the allocator error); the
    oom_guard must capture a crash-safe forensics bundle (census, active
    plan, watermark) BEFORE the error kills the worker, the monitor must
    page ``oom-detected`` (or ``hbm-pressure``) inside the alert budget,
    and the job must complete after the launcher restages the gang off
    the emergency checkpoint — an OOM costs a restage, never the run.

    Pacing: the fault lands at step 10 and the restage takes ~7s (grace
    + the failed pod's leave-hold + drain), so ``total`` must keep the
    shard-committing rank busy past the restage; the respawned stage
    resumes from the last periodic checkpoint and the ledger closes.
    Rank 1 is the victim (like worker-kill): after the shrink to
    world=1 no process matches, so the drill OOMs exactly once."""
    total, ckpt_every = 40, 5
    spec = {
        "seed": rig.seed,
        "rules": [
            # deep enough into training that the plan is harvested and
            # the census/monitor windows are primed with clean samples
            {"point": "train.mem.oom", "proc": "worker",
             "action": "drop", "match": {"rank": "1"}, "after": 10,
             "times": 1},
        ],
    }
    harness = rig.harness(
        spec, nodes_range="1:2", ttl=0.8, total=total,
        ckpt_every=ckpt_every, step_time=0.25,
        # census every 4 steps: the live-buffer evidence (and the CPU
        # rig's watermark stand-in) accrues within the drill's length;
        # the grace holds the dying worker's /metrics endpoint up for a
        # few 0.4s monitor sweeps so the terminal oom counter is scraped
        extra={"EDL_MEM_CENSUS_EVERY": "4", "EDL_CHAOS_OOM_GRACE": "1.5"},
    )
    try:
        done = harness.run_schedule([2], interval=3.0, timeout=180.0)
    finally:
        harness.shutdown()
    ev = rig.evidence()
    alerts = rig.alerts()
    flight = rig.flight_events()
    ooms = [
        e for e in ev.chaos_log
        if e.get("point") == "train.mem.oom" and e.get("action") == "drop"
    ]
    fault_ts = min((float(e.get("ts", 0.0)) for e in ooms), default=0.0)
    results = [
        # the contract under test: an OOM costs a restage, never the run
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.fault_injected(ev, "train.mem.oom", "drop"),
        inv.oom_forensics_captured(flight),
        inv.alert_fired_any(
            alerts, ["oom-detected", "hbm-pressure"],
            fault_ts, ALERT_LATENCY_BUDGET_S,
        ),
        # the OOM'd worker died: the job went through >= 2 stages
        inv.multiple_stages(ev),
    ]
    # archive rollups: the run's high-water mark and plan-vs-actual
    # score, from the flight evidence (on the CPU rig the census byte
    # total IS the residency signal — see obs/memory._sample_stats)
    plan_bytes = max(
        (float(e.get("total_bytes", 0.0)) for e in flight
         if e.get("event") == "mem_plan"), default=0.0,
    )
    peak_bytes = max(
        [float(e.get("peak_bytes", 0.0)) for e in flight
         if e.get("event") == "oom"]
        + [float(e.get("live_bytes", 0.0)) for e in flight
           if e.get("event") == "mem_census"]
        + [0.0],
    )
    accuracy = (
        100.0 * min(plan_bytes, peak_bytes) / max(plan_bytes, peak_bytes)
        if plan_bytes > 0 and peak_bytes > 0 else 0.0
    )
    return _outcome(
        "hbm-oom", rig.seed, results,
        harness_completed=done, fault_ts=fault_ts,
        alerts_fired=sorted(alerts),
        rollups={
            "hbm_peak_gb": round(peak_bytes / 1e9, 9),
            "hbm_plan_accuracy_pct": round(accuracy, 2),
        },
    )


PROMOTION_BUDGET_S = 15.0  # primary kill -> standby serving (CPU-rig bound)


def store_failover(rig: Rig) -> ScenarioOutcome:
    """The PRIMARY STORE dies mid-job (crash, not clean stop). The warm
    standby must promote within budget with an epoch bump; every write
    the old primary acked must survive with its revision; the job's
    clients must fail over and finish training with shards exactly-once;
    a watch held across the failover must see every event exactly once;
    and the resurrected old primary must be fenced before it can serve."""
    from edl_tpu.chaos.consistency import ConsistencyChurn
    from edl_tpu.store.server import StoreServer
    from edl_tpu.utils.exceptions import EdlStoreError

    total, ckpt_every = 24, 3
    # ttl comfortably above the failover window so the control-plane
    # outage is INVISIBLE to the job — no drain, no restage, just a
    # paused heartbeat; the shard ledger would catch any double-commit
    # if the job did restage
    harness = rig.harness(
        None, nodes_range="1:1", ttl=2.5, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
    )
    shard_prefix = chaos.chaos_prefix(rig.job_id) + "progress/shard/"
    acked_key = chaos.chaos_prefix(rig.job_id) + "failover/acked"
    seen: List = []
    watch = rig.client.watch(shard_prefix, lambda evs: seen.extend(evs))
    # standby-mode churn: reads prefer the standby across the failover,
    # and the history checker must still find the session linearizable
    churn = ConsistencyChurn(
        rig.store_endpoints, rig.flight_dir, read_mode="standby"
    )
    promote_s = None
    fenced_epoch = None
    probe_refused = False
    old_primary = None
    try:
        harness.start_pod()
        assert rig.wait_cursor(2 * ckpt_every, timeout=90.0), (
            "trainee never reached step %d (cursor %d)"
            % (2 * ckpt_every, rig.cursor())
        )
        acked_rev = rig.client.put(acked_key, b"must-survive")
        t0 = time.monotonic()
        rig.store.kill()  # machine death: no clean-stop snapshot
        deadline = time.monotonic() + PROMOTION_BUDGET_S
        while (
            time.monotonic() < deadline and rig.standby.role != "primary"
        ):
            time.sleep(0.05)
        if rig.standby.role == "primary":
            promote_s = time.monotonic() - t0
        # resurrect the dead primary on its own stale state, at the SAME
        # endpoint every client lists first: the promoted primary's fence
        # campaign must shut it out
        old_primary = StoreServer(
            host="127.0.0.1", port=rig.primary_port,
            data_dir=rig.primary_dir,
        ).start()
        deadline = time.monotonic() + PROMOTION_BUDGET_S
        while (
            time.monotonic() < deadline and old_primary._fenced_by is None
        ):
            time.sleep(0.05)
        fenced_epoch = old_primary._fenced_by
        probe = StoreClient(old_primary.endpoint, timeout=3.0, reconnect=False)
        try:
            probe.request("put", k="/fence/probe", v=b"intruder", l=0)
        except EdlStoreError:
            probe_refused = True
        finally:
            probe.close()
        done = harness.run_schedule([], interval=1.0, timeout=150.0)
    finally:
        harness.shutdown()
        watch.cancel()
        if old_primary is not None:
            old_primary.stop()
    consistency_results = _consistency_results(rig, churn)
    acked = rig.client.retrying("get", k=acked_key)
    ev = rig.evidence()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        inv.promoted_within(promote_s, PROMOTION_BUDGET_S),
        inv.acked_write_survived(
            acked.get("v"), b"must-survive", acked.get("mr", 0), acked_rev
        ),
        inv.stale_primary_fenced(
            fenced_epoch, probe_refused, rig.standby._state.epoch
        ),
        inv.watch_resumed_exactly_once(seen, shard_prefix, total),
        *consistency_results,
    ]
    return _outcome(
        "store-failover", rig.seed, results,
        harness_completed=done, promote_s=promote_s,
        promoted_epoch=rig.standby._state.epoch,
    )


store_failover.ha = True  # run_scenario builds the primary+standby rig


def store_shard_failover(rig: Rig) -> ScenarioOutcome:
    """EVERY shard primary of a 2-shard control plane dies mid-job
    (crash, not clean stop). Each shard's warm standby must promote
    independently within budget with its own epoch bump; an acked write
    ON EACH SHARD must survive with its original revision — semi-sync
    holds the ack until the standby applied+journaled, so this is a
    STRICT zero-loss invariant, not best-effort; and the job must
    finish training through the all-shards failover with shards
    exactly-once."""
    from edl_tpu.chaos.consistency import ConsistencyChurn

    total, ckpt_every = 24, 3
    # ttl comfortably above the failover window, as in store-failover:
    # the control-plane outage must be invisible to the job
    harness = rig.harness(
        None, nodes_range="1:1", ttl=2.5, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
    )
    acked: Dict[str, tuple] = {}  # shard name -> (key, acked rev)
    promotes: List[Optional[float]] = []
    # one standby-mode churn per shard, each pinned to its own pair and
    # probe prefix — the checker judges every /cp/ key independently
    churns = [
        ConsistencyChurn(
            "%s,%s" % (p.endpoint, s.endpoint), rig.flight_dir,
            prefix="/cp/s%d/" % i, read_mode="standby",
        )
        for i, (p, s) in enumerate(rig.shard_servers)
    ]
    try:
        harness.start_pod()
        assert rig.wait_cursor(2 * ckpt_every, timeout=90.0), (
            "trainee never reached step %d (cursor %d)"
            % (2 * ckpt_every, rig.cursor())
        )
        # one must-survive write PER SHARD: walk routing tokens until
        # the ring has handed us a key on every shard
        i = 0
        while len(acked) < len(rig.shard_servers) and i < 128:
            key = "/%s/failover%d/acked" % (rig.job_id, i)
            shard = rig.client.shard_of(key)
            if shard not in acked:
                rev = rig.client.put(key, b"must-survive")
                acked[shard] = (key, rev)
            i += 1
        assert len(acked) == len(rig.shard_servers), (
            "ring never covered every shard: %s" % sorted(acked)
        )
        t0 = time.monotonic()
        for primary, _standby in rig.shard_servers:
            primary.kill()  # machine death: no clean-stop snapshot
        deadline = time.monotonic() + PROMOTION_BUDGET_S
        for _primary, standby in rig.shard_servers:
            while (
                time.monotonic() < deadline and standby.role != "primary"
            ):
                time.sleep(0.05)
            promotes.append(
                time.monotonic() - t0
                if standby.role == "primary" else None
            )
        done = harness.run_schedule([], interval=1.0, timeout=150.0)
    finally:
        harness.shutdown()
    consistency_results = _consistency_results(rig, *churns)
    ev = rig.evidence()
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        *consistency_results,
    ]
    for promote_s in promotes:
        results.append(inv.promoted_within(promote_s, PROMOTION_BUDGET_S))
    for shard, (key, rev) in sorted(acked.items()):
        got = rig.client.retrying("get", k=key)
        results.append(inv.acked_write_survived(
            got.get("v"), b"must-survive", got.get("mr", 0), rev
        ))
    return _outcome(
        "store-shard-failover", rig.seed, results,
        harness_completed=done, promotes_s=promotes,
        shards=sorted(acked),
        epochs=[s._state.epoch for _p, s in rig.shard_servers],
    )


store_shard_failover.ha = True
store_shard_failover.shards = 2  # run_scenario builds a 2-shard rig


def store_consistency_red(rig: Rig) -> ScenarioOutcome:
    """RED DRILL: prove the consistency checker has teeth. With MVCC
    released-revision reads DISABLED (``EDL_STORE_MVCC=0``, set by
    run_scenario before the rig boots), a read during an open semi-sync
    window observes an applied-but-unacked write; when the primary then
    dies before the standby ack, failover rolls the keyspace back and
    the same session later reads the OLDER value — a non-monotonic
    session read the checker MUST flag. The scenario is red-on-green:
    it passes only when the anomaly is reproduced, so a checker that
    goes blind fails the drill."""
    import edl_tpu.chaos.consistency as cons
    from edl_tpu.utils.exceptions import EdlStoreError

    key = "/cp/x"
    # the session under test: taped, endpoints spanning the failover
    sess = StoreClient(
        rig.store_endpoints, timeout=5.0, op_tape_dir=rig.flight_dir
    )
    promote_s = None
    writer = None
    try:
        rev_a = sess.put(key, b"A")  # acked: applied+journaled on standby
        deadline = time.monotonic() + 10.0
        while (
            time.monotonic() < deadline
            and rig.standby._state.revision < rev_a
        ):
            time.sleep(0.02)
        # hold the semi-sync window open: acks wait far longer than the
        # drill runs, and the standby stops applying frames entirely
        rig.store._repl_sync_timeout = 30.0
        rig.standby._repl_apply = lambda frame: None  # wedge
        # indeterminate write: B applies on the primary but the ack
        # never comes back before the client gives up
        writer = StoreClient(
            rig.store.endpoint, timeout=0.6, reconnect=False,
            op_tape_dir=rig.flight_dir,
        )
        try:
            writer.put(key, b"B")
        except EdlStoreError:
            pass  # taped as indeterminate — exactly the point
        # the dirty read: with MVCC off the server answers from applied
        # state, so the session observes B inside the open window
        dirty = sess.get(key)
        t0 = time.monotonic()
        rig.store.kill()  # B dies with the primary
        deadline = time.monotonic() + PROMOTION_BUDGET_S
        while (
            time.monotonic() < deadline and rig.standby.role != "primary"
        ):
            time.sleep(0.05)
        if rig.standby.role == "primary":
            promote_s = time.monotonic() - t0
        # post-failover traffic, then the session re-reads the key: the
        # promoted standby never had B, so the session's view regresses
        for i in range(3):
            sess.retrying("put", k="/cp/fill%d" % i, v=b"f")
        final = sess.retrying("get", k=key)
    finally:
        if writer is not None:
            writer.close()
        sess.close()
    report = cons.check_history(rig.flight_events())
    cons.record_verdict(report, rig.flight_dir)
    results = [
        inv.promoted_within(promote_s, PROMOTION_BUDGET_S),
        inv.consistency_anomaly_reproduced(report),
    ]
    return _outcome(
        "store-consistency-red", rig.seed, results,
        dirty_value=(dirty or b"").decode("utf-8", "replace"),
        final_value=(final.get("v") or b"").decode("utf-8", "replace"),
        violations=report.violations[:8],
        promote_s=promote_s,
    )


store_consistency_red.ha = True
# the whole point: boot the pair WITHOUT released-revision reads
store_consistency_red.env = {"EDL_STORE_MVCC": "0"}


def corrupt_checkpoint_version(ckpt_dir: str, step: int) -> None:
    """Tear one checkpoint version on disk: every file under it is
    overwritten with garbage (the torn-write simulation shared by the
    corrupt-ckpt scenario and tests/test_checkpoint.py)."""
    root = os.path.join(ckpt_dir, str(step))
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            path = os.path.join(dirpath, name)
            try:
                size = os.path.getsize(path)
                with open(path, "wb") as f:
                    f.write(b"\xde\xad" * max(1, size // 2))
            except OSError:
                pass
    logger.warning("corrupted checkpoint version %d under %s", step, root)


def corrupt_latest_checkpoint(ckpt_dir: str) -> Optional[int]:
    """Tear the newest finalized version; returns its step (None if no
    versions exist yet)."""
    try:
        steps = sorted(
            int(name) for name in os.listdir(ckpt_dir) if name.isdigit()
        )
    except OSError:
        return None
    if not steps:
        return None
    corrupt_checkpoint_version(ckpt_dir, steps[-1])
    return steps[-1]


# -- scale plane drills -------------------------------------------------------

SCALE_DECISION_BUDGET_S = 30.0   # scale_decision fsync -> scale_reconcile
AUTOSCALE_LOSS_BOUND_PCT = 65.0  # realized vs oracle modeled goodput


def _scale_goodput_trace(
    events: list,
    phases: list,
    end_ts: float,
    params,
    min_world: int,
    max_world: int,
) -> tuple:
    """``(achieved, oracle)`` modeled-goodput integrals over one run.

    The realized schedule is read off the launcher's flight records — a
    ``publish`` sets the world, a ``drain`` zeroes it until the next
    publish (the restage gap trains nothing). The oracle replays the
    same signal trace (``phases`` = [(ts, gns, available pods)]) with
    zero decision latency and free restages: at every instant it runs
    the model argmax for the gns then in force. Both integrals use the
    SAME goodput model, so their ratio isolates scheduler quality."""
    from edl_tpu.scale import decide as scale_decide

    points = []
    for e in sorted(events, key=lambda ev: float(ev.get("ts", 0.0))):
        if e.get("event") == "publish":
            points.append((float(e.get("ts", 0.0)), int(e.get("pods", 0))))
        elif e.get("event") == "drain":
            points.append((float(e.get("ts", 0.0)), 0))
    if not points or not phases or end_ts <= points[0][0]:
        return 0.0, 0.0
    t0 = points[0][0]
    cuts = sorted(
        {t0, end_ts}
        | {ts for ts, _w in points if t0 < ts < end_ts}
        | {ts for ts, _g, _a in phases if t0 < ts < end_ts}
    )
    achieved = oracle = 0.0
    for a, b in zip(cuts, cuts[1:]):
        world = 0
        for ts, w in points:
            if ts <= a:
                world = w
        gns, avail = phases[0][1], phases[0][2]
        for ts, g, av in phases:
            if ts <= a:
                gns, avail = g, av
        stats = scale_decide.JobStats(world=max(world, 1), gns=gns)
        achieved += (b - a) * scale_decide.model_goodput(world, params, stats)
        best = scale_decide.best_world(
            min_world, min(max_world, avail), params, stats
        )
        oracle += (b - a) * scale_decide.model_goodput(best, params, stats)
    return achieved, oracle


def autoscale_churn(rig: Rig) -> ScenarioOutcome:
    """The goodput-driven autoscaler against a seeded signal trace.

    A live Scaler daemon watches the job while the scenario swings the
    two inputs the model ranks worlds by — pool capacity and the
    gradient-noise-scale — through a grow (capacity appears, the held
    pod is admitted via an autoscale-cause restage), a shrink (noise
    collapses, the model says 1 pod, the leader publishes autoscale
    preempt notices and the victims DRAIN out), a regrow, and an
    external spot reclaim (SIGTERM — attributed to membership, NOT
    autoscale). Gates: the job completes exactly-once, every decision
    the launcher reconciled closed within the latency budget, and the
    realized schedule's modeled goodput stays within the loss bound of
    the offline oracle replaying the same trace."""
    import random as _random
    import signal as _signal

    from edl_tpu.discovery.registry import Registry
    from edl_tpu.scale import decide as scale_decide
    from edl_tpu.scale import scaler as scale_scaler

    total, ckpt_every = 36, 4
    rnd = _random.Random(rig.seed)
    # rich noise scale: big batches stay efficient, optimum = capacity;
    # poor: efficiency collapses, optimum = 1 pod (seeded jitter keeps
    # both regimes decisively on their side of the hysteresis margin)
    gns_rich = 24.0 + 16.0 * rnd.random()
    gns_poor = 0.02 + 0.03 * rnd.random()
    params = scale_decide.ScaleParams(
        alpha=0.05, gns=gns_rich, hysteresis=0.02, cooldown_s=3.0
    )
    state = {"cap": 2, "gns": gns_rich}
    phases: list = []  # (ts, gns, available pods) — the oracle's trace

    def shift(cap=None, gns=None, avail=None):
        if cap is not None:
            state["cap"] = cap
        if gns is not None:
            state["gns"] = gns
        phases.append((
            time.time(), state["gns"],
            avail if avail is not None else state["cap"],
        ))

    # ttl HIGH: every world change must come from the scale/drain
    # planes (targets, preempt notices), never from lease expiry
    harness = rig.harness(
        None, nodes_range="1:3", ttl=5.0, total=total,
        ckpt_every=ckpt_every, step_time=0.2,
        extra={
            "EDL_HEARTBEAT_EVERY": "0.05",
            "EDL_DRAIN_BUDGET": str(DRAIN_BUDGET_S),
        },
    )
    scaler = scale_scaler.Scaler(
        rig.store_endpoints,
        [scale_scaler.JobSpec(rig.job_id, min_world=1, max_world=3)],
        interval=0.5,
        capacity=lambda: state["cap"],
        params=params,
        flight_dir=rig.flight_dir,
        trace_dir=rig.trace_dir,
        # pin the model inputs to the scenario's trace: world and
        # goodput stay REAL, the signals are the seeded schedule
        stats_override=lambda _job: {
            "gns": state["gns"], "per_pod_rate": 1.0, "goodput_ratio": 1.0,
        },
        scrape_timeout=0.5,
    )
    reg = Registry(rig.client, rig.job_id)

    def target_pods():
        try:
            meta = reg.get_server("scale", "target")
            if meta is None:
                return None
            return int(json.loads(meta.value.decode()).get("pods", -1))
        except Exception:  # noqa: BLE001 — store mid-churn
            return None

    def publishes(world=None):
        return [
            e for e in rig.flight_events()
            if e.get("event") == "publish"
            and (world is None or int(e.get("pods", 0)) == world)
        ]

    def wait_for(cond, timeout, what):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if cond():
                return
            time.sleep(0.2)
        raise AssertionError("timed out waiting for %s" % what)

    def reap():
        for proc in list(harness.pods):
            if proc.poll() is not None:
                harness.pods.remove(proc)

    try:
        # bootstrap at 2 pods, scaler quiet (capacity 2, already there)
        harness.start_pod()
        harness.start_pod()
        assert rig.wait_cursor(2, timeout=90.0), (
            "world-2 never stepped (cursor %d)" % rig.cursor()
        )
        shift()  # open the oracle trace: capacity 2, rich gns
        scaler.start()
        # GROW: a third pod's worth of capacity appears; the decision
        # must land before the pod does — arrival admits the held pod
        shift(cap=3)
        wait_for(lambda: target_pods() == 3, 30.0, "grow target")
        harness.start_pod()
        wait_for(lambda: publishes(3), 90.0, "world-3 stage")
        floor = rig.cursor() + 2
        assert rig.wait_cursor(floor, timeout=60.0), "world-3 never stepped"
        # SHRINK: gradient noise collapses -> the model says extra pods
        # buy wasted epochs -> autoscale preempt notices drain 2 pods
        shift(gns=gns_poor)
        wait_for(lambda: target_pods() == 1, 30.0, "shrink target")
        wait_for(lambda: publishes(1), 90.0, "world-1 stage")
        deadline = time.time() + 30
        while time.time() < deadline and len(harness.pods) > 1:
            reap()
            time.sleep(0.2)
        assert len(harness.pods) == 1, (
            "autoscale victims did not exit (%d pods left)"
            % len(harness.pods)
        )
        floor = rig.cursor() + 2
        assert rig.wait_cursor(floor, timeout=60.0), "world-1 never stepped"
        # REGROW: noise recovers; two replacement pods arrive
        n3 = len(publishes(3))
        shift(gns=gns_rich)
        wait_for(lambda: target_pods() == 3, 30.0, "regrow target")
        harness.start_pod()
        harness.start_pod()
        wait_for(lambda: len(publishes(3)) > n3, 90.0, "world-3 restage")
        # SPOT RECLAIM: an EXTERNAL SIGTERM (not the scaler's doing) —
        # the pool genuinely shrank, so the trace shrinks with it
        reap()
        victim = harness.pods[-1]
        victim.send_signal(_signal.SIGTERM)
        victim.wait()
        harness.pods.remove(victim)
        shift(cap=2)
        done = harness.run_schedule([], interval=1.0, timeout=240.0)
        end_ts = time.time()
        ev = rig.evidence()
    finally:
        scaler.stop()
        harness.shutdown()
    events = rig.flight_events()
    achieved, oracle = _scale_goodput_trace(
        events, phases, end_ts, params, 1, 3
    )
    loss_pct = 100.0 * (1.0 - achieved / oracle) if oracle > 0 else 100.0
    latencies = inv.scale_reconcile_latencies(events)
    worst_latency = max(latencies.values()) if latencies else -1.0
    results = [
        inv.completed(ev, total),
        inv.shards_exactly_once(ev, total),
        inv.replay_bounded(ev, ckpt_every),
        inv.multiple_stages(ev, at_least=4),
        # the scaler's moves went through the drain plane, attributed:
        # the grow + shrink restages advance the autoscale-cause counter
        inv.metric_advanced(
            ev, "edl_launch_drains_total", at_least=2,
            label_substr="autoscale",
        ),
        inv.scale_decision_latency(events, SCALE_DECISION_BUDGET_S),
        inv.autoscale_goodput_bounded(
            achieved, oracle, AUTOSCALE_LOSS_BOUND_PCT
        ),
        inv.gang_atomic_worlds(events, 1),
        inv.goodput_accounted(events),
        inv.critical_path_traced(rig.trace_spans(), events),
        inv.numerics_continuous(events),
    ]
    return _outcome(
        "autoscale-churn", rig.seed, results,
        harness_completed=done,
        decisions_reconciled=len(latencies),
        gns_rich=round(gns_rich, 2), gns_poor=round(gns_poor, 3),
        achieved=round(achieved, 2), oracle=round(oracle, 2),
        rollups={
            "autoscale_goodput_loss_pct": round(loss_pct, 1),
            "decision_to_restage_s": round(worst_latency, 2),
        },
    )


def autoscale_multijob(rig: Rig) -> ScenarioOutcome:
    """Two elastic jobs, ONE shared 3-pod pool, one arbiter.

    Job A (priority 0, min 1) runs at the full pool. Job B (priority
    10, min=max=2, short) is submitted mid-flight: the arbiter's
    admission preempts A down to 1 via autoscale preempt notices, and
    gang sequencing holds B's grow until A's freed pods are REAL — B's
    launchers hold their pods at want=0 (the queued target) and only
    publish once released, so B's first stage strictly follows A's
    shrink and neither job ever publishes below its min world. When B
    completes, its bid dissolves and A regrows onto the freed pool."""
    import signal as _signal  # noqa: F401 — parity with sibling drills

    from edl_tpu.discovery.registry import Registry
    from edl_tpu.harness.resize import ResizeHarness as _ResizeHarness
    from edl_tpu.obs import events as obs_events
    from edl_tpu.scale import decide as scale_decide
    from edl_tpu.scale import scaler as scale_scaler

    total_a, ckpt_a = 120, 6
    total_b, ckpt_b = 8, 4
    job_b = rig.job_id + "-b"
    b_flight = os.path.join(rig.workdir, "b-flight")
    b_trace = os.path.join(rig.workdir, "b-traces")
    params = scale_decide.ScaleParams(
        alpha=0.05, gns=30.0, hysteresis=0.02, cooldown_s=2.0
    )
    harness_a = rig.harness(
        None, nodes_range="1:3", ttl=5.0, total=total_a,
        ckpt_every=ckpt_a, step_time=0.2,
        extra={
            "EDL_HEARTBEAT_EVERY": "0.05",
            "EDL_DRAIN_BUDGET": str(DRAIN_BUDGET_S),
        },
    )
    env_b = dict(rig.job_env)
    env_b.update({
        "EDL_CHAOS_LOG": os.path.join(rig.workdir, "chaos-b.log"),
        "EDL_CKPT_PATH": os.path.join(rig.workdir, "ckpt-b"),
        "EDL_FLIGHT_DIR": b_flight,
        "EDL_TRACE_DIR": b_trace,
        "EDL_CHAOS_TOTAL_STEPS": str(total_b),
        "EDL_CHAOS_CKPT_EVERY": str(ckpt_b),
    })
    harness_b = _ResizeHarness(
        rig.store_endpoints, job_b, TRAINEE,
        nodes_range="2:2",  # the gang floor, enforced structurally too
        ttl=5.0,
        log_dir=os.path.join(rig.workdir, "logs-b"),
        extra_env=env_b,
    )
    scaler = scale_scaler.Scaler(
        rig.store_endpoints,
        [scale_scaler.JobSpec(rig.job_id, min_world=1, max_world=3,
                              priority=0)],
        interval=0.5,
        capacity=3,
        params=params,
        flight_dir=rig.flight_dir,
        trace_dir=rig.trace_dir,
        stats_override=lambda _job: {
            "gns": 30.0, "per_pod_rate": 1.0, "goodput_ratio": 1.0,
        },
        scrape_timeout=0.5,
    )

    def target_of(job_id):
        try:
            meta = Registry(rig.client, job_id).get_server("scale", "target")
            if meta is None:
                return None
            return int(json.loads(meta.value.decode()).get("pods", -1))
        except Exception:  # noqa: BLE001
            return None

    def job_status(job_id):
        try:
            return rig.client.get("/%s/job/status" % job_id)
        except Exception:  # noqa: BLE001
            return None

    def pubs(events, world=None):
        return [
            e for e in events
            if e.get("event") == "publish"
            and (world is None or int(e.get("pods", 0)) == world)
        ]

    def wait_for(cond, timeout, what):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if cond():
                return
            time.sleep(0.2)
        raise AssertionError("timed out waiting for %s" % what)

    def reap(harness):
        for proc in list(harness.pods):
            if proc.poll() is not None:
                harness.pods.remove(proc)

    regrew = False
    try:
        # job A owns the whole pool first
        for _ in range(3):
            harness_a.start_pod()
        assert rig.wait_cursor(2, timeout=120.0), (
            "job A never stepped (cursor %d)" % rig.cursor()
        )
        wait_for(lambda: pubs(rig.flight_events(), 3), 60.0, "A at world 3")
        scaler.start()
        # SUBMIT job B: the queued target (0 pods) lands before its
        # pods do — arrival is not admission
        scaler.add_job(scale_scaler.JobSpec(
            job_b, min_world=2, max_world=2, priority=10,
        ))
        harness_b.start_pod()
        harness_b.start_pod()
        # admission preempts A down to 1 (priority beats incumbency)...
        wait_for(lambda: target_of(rig.job_id) == 1, 30.0,
                 "A's preemption target")
        wait_for(lambda: pubs(rig.flight_events(), 1), 90.0,
                 "A's world-1 stage")
        # ...and only THEN is B's gang released onto the freed pods
        wait_for(lambda: target_of(job_b) == 2, 30.0, "B's release target")
        wait_for(lambda: pubs(obs_events.read_segments(b_flight), 2),
                 90.0, "B's world-2 stage")
        wait_for(lambda: job_status(job_b) == b"COMPLETE", 120.0,
                 "B completion")
        # B's bid dissolves -> A regrows onto the freed pool (unless A
        # already finished during the held window — seeds vary)
        deadline = time.time() + 45
        while time.time() < deadline:
            if job_status(rig.job_id) == b"COMPLETE":
                break
            if target_of(rig.job_id) == 3:
                regrew = True
                reap(harness_a)
                harness_a.start_pod()
                harness_a.start_pod()
                break
            time.sleep(0.3)
        done_a = harness_a.run_schedule([], interval=1.0, timeout=300.0)
        ev_a = rig.evidence()
        ev_b = inv.Evidence(
            progress=inv.read_progress(rig.client, job_b),
            telemetry=telemetry.collect(rig.client, job_b),
        )
    finally:
        scaler.stop()
        harness_b.shutdown()
        harness_a.shutdown()
    a_events = rig.flight_events()
    b_events = obs_events.read_segments(b_flight)
    merged = a_events + b_events
    latencies = inv.scale_reconcile_latencies(merged)
    worst_latency = max(latencies.values()) if latencies else -1.0
    preempts = [e for e in a_events if e.get("event") == "scale_preempt"]
    a_shrunk_ts = min(
        (float(e["ts"]) for e in pubs(a_events, 1)), default=None
    )
    b_first_ts = min(
        (float(e["ts"]) for e in pubs(b_events)), default=None
    )
    ordered = (
        a_shrunk_ts is not None
        and b_first_ts is not None
        and a_shrunk_ts <= b_first_ts
    )

    def tag(result, suffix):
        result.name += suffix
        return result

    results = [
        tag(inv.completed(ev_a, total_a), "[a]"),
        tag(inv.shards_exactly_once(ev_a, total_a), "[a]"),
        tag(inv.replay_bounded(ev_a, ckpt_a), "[a]"),
        tag(inv.completed(ev_b, total_b), "[b]"),
        tag(inv.shards_exactly_once(ev_b, total_b), "[b]"),
        inv.InvariantResult(
            "autoscale_preempted",
            len(preempts) >= 2,
            "%d scale_preempt notice(s) for job A (want >= 2)"
            % len(preempts),
        ),
        inv.metric_advanced(
            ev_a, "edl_launch_drains_total", at_least=1,
            label_substr="autoscale",
        ),
        inv.InvariantResult(
            "priority_admission_ordered",
            ordered,
            "A shrank at %s, B first published at %s"
            % (a_shrunk_ts, b_first_ts),
        ),
        tag(inv.gang_atomic_worlds(a_events, 1), "[a]"),
        tag(inv.gang_atomic_worlds(b_events, 2), "[b]"),
        inv.scale_decision_latency(merged, SCALE_DECISION_BUDGET_S),
        inv.goodput_accounted(a_events),
    ]
    return _outcome(
        "autoscale-multijob", rig.seed, results,
        harness_a_completed=done_a, regrew=regrew,
        decisions_reconciled=len(latencies),
        rollups={"decision_to_restage_s": round(worst_latency, 2)},
    )


SCENARIOS: Dict[str, Callable[[Rig], ScenarioOutcome]] = {
    "worker-kill": worker_kill,
    "store-blip": store_blip,
    "corrupt-ckpt": corrupt_checkpoint,
    "slow-rpc": slow_rpc,
    "teacher-failover": teacher_failover,
    "serve-slo-churn": serve_slo_churn,
    "store-failover": store_failover,
    "store-shard-failover": store_shard_failover,
    "store-consistency-red": store_consistency_red,
    "ckpt-peer-loss": ckpt_peer_loss,
    "preempt-drain": preempt_drain,
    "straggler-stall": straggler_stall,
    "monitor-clean": monitor_clean,
    "grad-corrupt": grad_corrupt,
    "hbm-oom": hbm_oom,
    "autoscale-churn": autoscale_churn,
    "autoscale-multijob": autoscale_multijob,
}


def run_scenario(
    name: str, seed: int, workdir: str, archive_to: Optional[str] = "auto"
) -> ScenarioOutcome:
    """Run one named scenario in a fresh rig under ``workdir``, then
    archive the run (flight segments, traces, monitor series, chaos
    ledger, invariant verdicts) into the run archive and assert the
    ``run_archived`` invariant: every scenario run is a comparable,
    indexed artifact ``edl-report`` can trend, diff, and gate.

    ``archive_to``: an explicit root (the soak runner passes ONE root
    so every seed lands in the same index), the default ``"auto"``
    (``EDL_RUN_ARCHIVE``, else ``{workdir}/runs``), or None — the
    caller opted out of archiving entirely, which also skips the
    invariant (an observability opt-out must not fail a successful
    recovery)."""
    fn = SCENARIOS.get(name)
    if fn is None:
        raise KeyError(
            "unknown scenario %r (have: %s)" % (name, ", ".join(sorted(SCENARIOS)))
        )
    # scenario-pinned env (e.g. the red drill's EDL_STORE_MVCC=0) must
    # be in place BEFORE the rig boots: the store reads it at construction
    env_over = getattr(fn, "env", None) or {}
    env_saved = {k: os.environ.get(k) for k in env_over}
    os.environ.update(env_over)
    t0 = time.monotonic()
    try:
        rig = Rig(
            os.path.join(workdir, name.replace("/", "_")),
            job_id="chaos-%s-%d" % (name, seed),
            seed=seed,
            ha=getattr(fn, "ha", False),
            shards=getattr(fn, "shards", 1),
        )
        try:
            outcome = fn(rig)
        finally:
            rig.close()  # monitor stopped -> series segments are final
    finally:
        for k, v in env_saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    outcome.info["duration_s"] = round(time.monotonic() - t0, 2)

    from edl_tpu.obs import archive as run_archive

    if archive_to == "auto":
        root = run_archive.archive_root(default=os.path.join(workdir, "runs"))
    else:
        root = archive_to
    bundle = None
    if root:
        try:
            bundle = run_archive.RunArchive(root).archive(
                "chaos-%s" % name,
                "s%d" % seed,
                backend="cpu",  # chaos scenarios are CPU-rig drills
                seed=seed,
                flight_dir=rig.flight_dir,
                trace_dir=rig.trace_dir,
                monitor_dir=rig.monitor_dir,
                chaos_log=rig.chaos_log,
                invariants=[
                    {"name": r.name, "ok": r.ok, "detail": r.detail}
                    for r in outcome.invariants
                ],
                # scenario-computed rollups (e.g. the autoscale drill's
                # goodput-loss-vs-oracle) trend beside the duration
                rollups=dict(
                    outcome.info.get("rollups", {}),
                    duration_s=outcome.info["duration_s"],
                ),
                knobs=run_archive.knob_snapshot(rig.job_env),
                extra={"scenario": name, "info": outcome.info},
            )
        except Exception as exc:  # noqa: BLE001 — the invariant reports it
            logger.warning("run archive failed for %s: %s", name, exc)
    if root:
        # the invariant only audits ARMED archiving: EDL_RUN_ARCHIVE=0
        # (or archive_to=None) opted out, and opting out of
        # observability must not turn a green recovery red
        outcome.invariants.append(
            inv.run_archived(bundle, os.path.join(root, run_archive.INDEX_NAME))
        )
        outcome.ok = all(r.ok for r in outcome.invariants)
    if bundle:
        outcome.info["bundle"] = os.path.basename(bundle)
    return outcome
