"""Attention: jnp reference + Pallas flash-attention TPU kernel.

The flash kernel streams KV blocks through VMEM with the online-softmax
recurrence (running row-max ``m``, denominator ``l``, numerator ``acc``),
so the [Tq, Tk] score matrix never materializes in HBM — the standard
memory-bandwidth win on TPU where HBM, not FLOPs, bounds attention.

Layout: ``[batch, heads, seq, head_dim]``. The kernel grid is
``(batch*heads, q_blocks)``; each program owns one q block and loops over
kv blocks with ``lax.fori_loop``. Causal masking compares global q/k
positions from ``broadcasted_iota`` (TPU needs ≥2D iota).

``flash_attention`` is differentiable via ``jax.custom_vjp``: the
backward pass recomputes with the jnp reference (flash-style backward
kernels are a later optimization; recompute-backward is the standard
memory/speed trade and matches ``jax.checkpoint`` behavior).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Plain softmax attention; [B, H, T, D] in, [B, H, Tq, D] out."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + (tk - tq)  # align ends
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# -- pallas kernel ----------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_block: int, seq_k: int, q_offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    block_q = q.shape[0]

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kv = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            # q_offset = tk - tq aligns sequence *ends*, matching
            # attention_reference's causal mask for cross-length inputs.
            qpos = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                + qi * q_block
                + q_offset
            )
            kpos = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                + j * block_k
            )
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
) -> jax.Array:
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]

    def fit(block: int, t: int) -> int:
        # largest divisor of t that is <= block and sublane-aligned, so a
        # large default block never disqualifies shapes a smaller one
        # handled (e.g. tk=768 with block_k=512 -> 256, not a fallback)
        block = min(block, t)
        while block > 8 and t % block:
            block //= 2
        return block

    block_q = fit(block_q, tq)
    block_k = fit(block_k, tk)
    if tq % block_q or tk % block_k:
        return attention_reference(q, k, v, causal=causal, scale=scale)

    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    grid = (b * h, tq // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            scale=scale,
            q_block=block_q,
            seq_k=tk,
            q_offset=tk - tq,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q, k, v: attention_reference(q, k, v, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 512,
) -> jax.Array:
    """Flash attention; falls back to the reference on ragged shapes.

    Default blocks come from an on-chip sweep (v5e, bf16, d=64, seq
    2k-4k, bench_results/attention_tpu_r2.jsonl): block_q=128 with
    block_k=512 was fastest at every sequence length tried, ~18% over
    128/128 at seq 4096 and at parity with jax's builtin TPU flash
    kernel in the same measurement window."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, causal, scale, block_q, block_k)
