"""Attention: jnp reference + Pallas flash-attention TPU kernel.

The flash kernel streams KV blocks through VMEM with the online-softmax
recurrence (running row-max ``m``, denominator ``l``, numerator ``acc``),
so the [Tq, Tk] score matrix never materializes in HBM — the standard
memory-bandwidth win on TPU where HBM, not FLOPs, bounds attention.

Layout: ``[batch, heads, seq, head_dim]``. The kernel grid is
``(batch*heads, q_blocks)``; each program owns one q block and loops over
kv blocks with ``lax.fori_loop``. Causal masking compares global q/k
positions from ``broadcasted_iota`` (TPU needs ≥2D iota).

``flash_attention`` is differentiable via ``jax.custom_vjp`` with REAL
flash backward kernels: the forward saves per-row logsumexp (``lse``),
the backward recomputes probabilities blockwise as ``exp(s - lse)`` (no
online-softmax rescan needed) and runs two Pallas kernels — one gridded
over q blocks producing ``dq``, one over kv blocks producing ``dk``/``dv``
— so the backward, where training time actually goes, also never
materializes the [Tq, Tk] score matrix. Causal runs skip fully-masked
blocks via dynamic ``fori_loop`` bounds. Ragged shapes fall back to the
jnp reference end-to-end (forward and backward agree by construction).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _dense_causal_mask(scores: jax.Array) -> jax.Array:
    """End-aligned causal mask for a dense [..., Tq, Tk] score tensor:
    ``qpos = arange(Tq) + (Tk - Tq)`` so sequence ENDS line up (the one
    convention every path in this module must share)."""
    tq, tk = scores.shape[-2], scores.shape[-1]
    qpos = jnp.arange(tq)[:, None] + (tk - tq)
    kpos = jnp.arange(tk)[None, :]
    return jnp.where(qpos >= kpos, scores, NEG_INF)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Plain softmax attention; [B, H, T, D] in, [B, H, Tq, D] out."""
    return attention_reference_with_lse(q, k, v, causal=causal, scale=scale)[0]


def _gqa_group(q: jax.Array, k: jax.Array) -> int:
    """q heads per kv head (1 = plain MHA). Every entry point accepts
    k/v with FEWER heads than q (GQA/MQA) as long as the count divides:
    the kernels read the grouped arrays directly via index mapping (no
    materialized repeat), and dk/dv come back at the grouped width."""
    h, h_kv = q.shape[1], k.shape[1]
    if h == h_kv:
        return 1
    if h_kv < 1 or h % h_kv:
        raise ValueError(
            "kv heads (%d) must divide q heads (%d)" % (h_kv, h)
        )
    return h // h_kv


def _broadcast_kv(q, k, v):
    g = _gqa_group(q, k)
    if g == 1:
        return k, v
    return jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)


def _fold_dkv(dk, dv, b, h_kv, group, tk, d):
    """Sum full-q-head-width dk/dv back to the grouped input width."""
    if group == 1:
        return dk, dv
    dk = dk.reshape(b, h_kv, group, tk, d).sum(axis=2)
    dv = dv.reshape(b, h_kv, group, tk, d).sum(axis=2)
    return dk, dv


def attention_reference_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
):
    """Reference attention that also returns per-row logsumexp of the
    scaled scores ``[B, H, Tq]`` — the residual blockwise/ring merging
    needs. Grouped k/v (GQA) broadcast in-graph; their VJP folds dk/dv
    back to the grouped width automatically."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    k, v = _broadcast_kv(q, k, v)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        scores = _dense_causal_mask(scores)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)
    return out, lse


# -- pallas kernel ----------------------------------------------------------
#
# Matmul operands stay in the INPUT dtype (bf16 in training) with fp32
# accumulation via preferred_element_type: the v5e MXU multiplies bf16 at
# full rate but fp32 at a fraction of it, and the round-4 kernels' cast-
# everything-to-fp32 habit measured ~30 TFLOP/s on a 197 TFLOP/s chip.
# Probabilities are cast back to the value dtype for the p@v / p.T@do
# products — exactly what attention_reference's ``probs.astype(v.dtype)``
# does, so kernel and reference share input precision. Softmax state,
# lse/delta and all accumulators remain fp32. The helpers below express
# the transposed products as dot_general contractions so no operand is
# materialized transposed in VMEM.


def _dot_nt(a, b):
    """``a [m, d] @ b [n, d].T -> fp32 [m, n]`` without a transpose."""
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_nn(a, b):
    """``a [m, k] @ b [k, n] -> fp32 [m, n]``."""
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tn(a, b):
    """``a [k, m].T @ b [k, n] -> fp32 [m, n]`` without a transpose."""
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _causal_mask(s, qi, q_block, j, block_k, q_offset):
    """Mask one [block_q, block_k] score tile; ``q_offset = tk - tq``
    aligns sequence *ends*, matching ``attention_reference``."""
    block_q = s.shape[0]
    qpos = (
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        + qi * q_block
        + q_offset
    )
    kpos = (
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        + j * block_k
    )
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, scale: float, q_block: int, seq_k: int,
                  q_offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0]  # [block_q, d], input dtype (bf16 rides the MXU fast path)
    block_q = q.shape[0]

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_kv = seq_k // block_k
    if causal:
        # kv blocks past this q block's last row are fully masked
        upper = jnp.minimum(
            num_kv, ((qi + 1) * q_block + q_offset + block_k - 1) // block_k
        )
    else:
        upper = num_kv

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot_nt(q, k_blk) * scale
        if causal:
            s = _causal_mask(s, qi, q_block, j, block_k, q_offset)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + _dot_nn(p.astype(v_blk.dtype), v_blk)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # per-row logsumexp of the SCALED scores: the backward's residual.
    # lse rides pallas as [B*H, Tq, 1] — a (1, block_q, 1) block keeps the
    # sublane dim 8-aligned, which the TPU lowering requires (a plain
    # (1, block_q) block over [B*H, Tq] has sublane 1 and is rejected)
    lse_ref[0] = m + jnp.log(l)


def _flash2_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                   acc_scr, *, causal: bool, scale: float, q_block: int,
                   block_k: int, num_k: int, q_offset: int):
    """Grid-pipelined forward: the KV loop lives in the GRID (innermost
    dimension), so Pallas double-buffers each KV block's HBM→VMEM copy
    behind the previous block's compute — where :func:`_flash_kernel`
    holds the WHOLE KV in VMEM and walks it with a serial ``fori_loop``
    (no copy/compute overlap, and a VMEM footprint that scales with the
    full sequence). Online-softmax state (m, l, acc) carries across the
    innermost grid steps in VMEM scratch, initialized at j==0 and
    finalized into (o, lse) at j==num_k-1."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # fully-masked (q_block, k_block) tiles skip the FLOPs (their DMA
    # already happened; the win of the in-kernel loop's block skipping is
    # traded for pipelining)
    live = True
    if causal:
        live = j * block_k <= (qi + 1) * q_block + q_offset - 1

    @pl.when(live)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = _dot_nt(q, k) * scale
        if causal:
            s = _causal_mask(s, qi, q_block, j, block_k, q_offset)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + _dot_nn(p.astype(v.dtype), v)

    @pl.when(j == num_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)  # [bq, 1] (see _flash_kernel)


def _grid_pipeline_kwargs() -> dict:
    """pallas_call kwargs shared by every flash2-family kernel: batch and
    the outer block dimension are independent ('parallel'); only the
    innermost accumulation walk is sequential ('arbitrary')."""
    from jax.experimental.pallas import tpu as pltpu

    try:
        return {
            "compiler_params": pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        }
    except (AttributeError, TypeError):
        return {}


def _bwd_delta(g: jax.Array, o: jax.Array, b: int, h: int, tq: int, d: int):
    """delta_i = sum_d dO_i O_i, in kernel layout — the softmax-jacobian
    row correction every backward kernel consumes."""
    return jnp.sum(
        g.reshape(b * h, tq, d).astype(jnp.float32)
        * o.reshape(b * h, tq, d).astype(jnp.float32),
        axis=-1,
    )


def _flash2_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """(o, lse) via the grid-pipelined kernel; same ragged fallback
    contract as :func:`_flash_forward` (``lse is None`` = dense path)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    if tq % block_q or tk % block_k or (causal and tq > tk):
        return attention_reference(q, k, v, causal=causal, scale=scale), None

    g = _gqa_group(q, k)
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * (h // g), tk, d)
    vf = v.reshape(b * (h // g), tk, d)
    num_k = tk // block_k
    grid = (b * h, tq // block_q, num_k)
    kwargs = _grid_pipeline_kwargs()
    out, lse = pl.pallas_call(
        functools.partial(
            _flash2_kernel,
            causal=causal,
            scale=scale,
            q_block=block_q,
            block_k=block_k,
            num_k=num_k,
            q_offset=tk - tq,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, j, g=g: (i // g, j, 0)
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, j, g=g: (i // g, j, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, qi, j: (i, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d), lse[..., 0]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool, scale: float,
                         q_block: int, seq_k: int, q_offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0]                                        # [bq, d]
    do = do_ref[0]                                      # [bq, d]
    lse = lse_ref[0]                                    # [bq, 1]
    delta = delta_ref[0]                                # [bq, 1]
    block_q = q.shape[0]

    num_kv = seq_k // block_k
    if causal:
        upper = jnp.minimum(
            num_kv, ((qi + 1) * q_block + q_offset + block_k - 1) // block_k
        )
    else:
        upper = num_kv

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot_nt(q, k_blk) * scale
        if causal:
            s = _causal_mask(s, qi, q_block, j, block_k, q_offset)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dp = _dot_nt(do, v_blk)
        ds = p * (dp - delta)
        return dq + _dot_nn(ds.astype(k_blk.dtype), k_blk)

    dq = jax.lax.fori_loop(
        0, upper, body, jnp.zeros((block_q, q.shape[1]), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float, k_block: int, seq_q: int,
                          q_offset: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    k_blk = k_ref[0]                                    # [bk, d]
    v_blk = v_ref[0]                                    # [bk, d]
    bk, d = k_blk.shape

    num_q = seq_q // block_q
    if causal:
        # q rows before this kv block's first column are fully masked
        lower = jnp.maximum(0, (ki * k_block - q_offset) // block_q)
    else:
        lower = 0

    def body(j, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(j * block_q, block_q), :]
        do = do_ref[0, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(j * block_q, block_q)]    # [bq, 1]
        delta = delta_ref[0, pl.ds(j * block_q, block_q)]
        s = _dot_nt(q_blk, k_blk) * scale
        if causal:
            s = _causal_mask(s, j, block_q, ki, k_block, q_offset)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dv = dv + _dot_tn(p.astype(do.dtype), do)
        dp = _dot_nt(do, v_blk)
        ds = p * (dp - delta)
        dk = dk + _dot_tn(ds.astype(q_blk.dtype), q_blk)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        lower, num_q, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
    )
    # scale was applied to s, not pre-folded into q, so dk takes its one
    # factor of ``scale`` here
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash2_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, dq_scr, *, causal: bool, scale: float,
                          q_block: int, block_k: int, num_k: int,
                          q_offset: int):
    """Grid-pipelined dq: KV blocks ride the innermost grid dimension
    (double-buffered DMA), dq accumulates in VMEM scratch across steps —
    the backward twin of :func:`_flash2_kernel`'s structure."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = True
    if causal:
        live = j * block_k <= (qi + 1) * q_block + q_offset - 1

    @pl.when(live)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                # [bq, 1]
        delta = delta_ref[0]
        s = _dot_nt(q, k) * scale
        if causal:
            s = _causal_mask(s, qi, q_block, j, block_k, q_offset)
        p = jnp.exp(s - lse)
        dp = _dot_nt(do, v)
        ds = p * (dp - delta)
        dq_scr[:] = dq_scr[:] + _dot_nn(ds.astype(k.dtype), k)

    @pl.when(j == num_k - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _flash2_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                           scale: float, block_q: int, k_block: int,
                           num_q: int, q_offset: int):
    """Grid-pipelined dk/dv: Q/dO/lse/delta blocks ride the innermost
    grid dimension, dk/dv accumulate in scratch per KV block."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = True
    if causal:
        # q blocks entirely before this kv block's first column are dead
        live = j >= jnp.maximum(0, (ki * k_block - q_offset) // block_q)

    @pl.when(live)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                # [bq, 1]
        delta = delta_ref[0]
        s = _dot_nt(q, k) * scale
        if causal:
            s = _causal_mask(s, j, block_q, ki, k_block, q_offset)
        p = jnp.exp(s - lse)
        dv_scr[:] = dv_scr[:] + _dot_tn(p.astype(do.dtype), do)
        dp = _dot_nt(do, v)
        ds = p * (dp - delta)
        dk_scr[:] = dk_scr[:] + _dot_tn(ds.astype(q.dtype), q)

    @pl.when(j == num_q - 1)
    def _finalize():
        # scale applied to s, not pre-folded into q (see
        # _flash_bwd_dkv_kernel): dk takes its one factor here
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash2_backward(
    q, k, v, o, lse, g, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """(dq, dk, dv) via the grid-pipelined backward kernels;
    ``lse`` in kernel layout [B*H, Tq] like :func:`_flash_backward`."""
    b, h, tq, d = q.shape
    delta = _bwd_delta(g, o, b, h, tq, d)
    return _flash2_backward_kernels(
        q, k, v, g, lse, delta, causal, scale, block_q, block_k, interpret
    )


def _flash2_backward_kernels(
    q, k, v, g, lse, delta, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """The two grid-pipelined backward pallas calls; ``lse``/``delta``
    are [B*H, Tq] (external residuals welcome — ring attention's
    per-rotation block grads route here past the whole-KV compile
    limit)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    grp = _gqa_group(q, k)
    h_kv = h // grp
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)

    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h_kv, tk, d)
    vf = v.reshape(b * h_kv, tk, d)
    gf = g.reshape(b * h, tq, d)
    # pallas layout: trailing singleton keeps the block sublane 8-aligned
    lse3 = lse[..., None]
    delta3 = delta[..., None]
    num_k = tk // block_k
    num_q = tq // block_q
    kwargs = _grid_pipeline_kwargs()
    common = dict(causal=causal, scale=scale, q_offset=tk - tq)

    dq = pl.pallas_call(
        functools.partial(
            _flash2_bwd_dq_kernel,
            q_block=block_q, block_k=block_k, num_k=num_k, **common,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        grid=(b * h, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, j, g=grp: (i // g, j, 0)
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, j, g=grp: (i // g, j, 0)
            ),
            pl.BlockSpec((1, block_q, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, qi, j: (i, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, qi, j: (i, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf, gf, lse3, delta3)

    # dk/dv at full q-head width, folded to the grouped width outside
    # (see _flash_backward_kernels)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash2_bwd_dkv_kernel,
            block_q=block_q, k_block=block_k, num_q=num_q, **common,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        grid=(b * h, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, ki, j: (i, j, 0)),
            pl.BlockSpec(
                (1, block_k, d), lambda i, ki, j, g=grp: (i // g, ki, 0)
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda i, ki, j, g=grp: (i // g, ki, 0)
            ),
            pl.BlockSpec((1, block_q, d), lambda i, ki, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, ki, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, ki, j: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf, gf, lse3, delta3)

    dk, dv = _fold_dkv(
        dk.reshape(b, h, tk, d), dv.reshape(b, h, tk, d),
        b, h_kv, grp, tk, d,
    )
    return dq.reshape(b, h, tq, d), dk, dv


_INF = float("inf")
# measured per-seq WHOLE-KV flash kernel blocks — v5e on-chip sweep
# (bq x bk grid, causal [4,16,T,64] bf16, bench_results/README.md
# "block sweep"): rows (max_seq, (fwd_bq, fwd_bk), (bwd_bq, bwd_bk)),
# first match wins (last row unbounded). bk=1024 crashes the TPU
# compiler at seq>=4096; the 512 column won or tied everywhere it
# mattered, so only bq varies. flash2 has its own separately-swept
# blocks (_FLASH2_BLOCKS_* below) — this table is whole-KV-only.
_BLOCK_TABLE = (
    (1024, (256, 512), (256, 512)),
    (2048, (512, 512), (256, 512)),
    (_INF, (128, 512), (512, 512)),
)


def _kernel_blocks(tq: int):
    """(fwd_blocks, bwd_blocks) for a sequence length, from the measured
    table; callers still pass the result through ``_fit_block``."""
    for max_seq, fwd, bwd in _BLOCK_TABLE:
        if tq <= max_seq:
            return fwd, bwd


# flash2 (grid-pipelined) blocks — swept separately at seq 8192 (the
# regime flash2 owns: the whole-KV kernel does not compile there).
# bk=1024 is safe for flash2 (KV streams through the grid, constant
# VMEM) where it crashed the compiler for the whole-KV kernel; the
# (128, 512) flash defaults left 2.4x fwd / 2.6x fwd+bwd on the table.
_FLASH2_BLOCKS_FWD = (256, 1024)
_FLASH2_BLOCKS_BWD = (512, 1024)


def _fit_block(block: int, t: int) -> int:
    # largest divisor of t that is <= block and sublane-aligned, so a
    # large default block never disqualifies shapes a smaller one
    # handled (e.g. tk=768 with block_k=512 -> 256, not a fallback)
    block = min(block, t)
    while block > 8 and t % block:
        block //= 2
    return block


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """Returns ``(o, lse)``; ``lse is None`` marks the ragged-shape
    fallback to the jnp reference (backward then uses the reference too)."""
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    if tq % block_q or tk % block_k or (causal and tq > tk):
        # ragged blocks, or end-aligned causal with MORE queries than keys:
        # the latter leaves early q rows with zero visible keys, where the
        # reference degenerates to a uniform softmax — not worth defeating
        # the kernel's masked-block skipping to reproduce
        return attention_reference(q, k, v, causal=causal, scale=scale), None

    g = _gqa_group(q, k)
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * (h // g), tk, d)
    vf = v.reshape(b * (h // g), tk, d)
    grid = (b * h, tq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            scale=scale,
            q_block=block_q,
            seq_k=tk,
            q_offset=tk - tq,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            # grouped k/v row: programs in one GQA group share it, so no
            # H-wide repeat ever materializes in HBM
            pl.BlockSpec((1, tk, d), lambda i, j, g=g: (i // g, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, g=g: (i // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d), lse[..., 0]


def _block_grads_reference(q, k, v, g, lse, delta, causal, scale):
    """jnp twin of the backward kernels for shapes they can't tile:
    block gradients given EXTERNAL (global) lse and delta."""
    b, h_kv, tk, d = k.shape
    grp = _gqa_group(q, k)
    k, v = _broadcast_kv(q, k, v)
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        s = _dense_causal_mask(s)
    p = jnp.exp(s - lse[..., None])
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    dp = jnp.einsum(
        "bhqd,bhkd->bhqk", g32, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    dk, dv = _fold_dkv(dk, dv, b, h_kv, grp, tk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def flash_block_grads(
    q, k, v, g, lse, delta,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
):
    """(dq, dk, dv) for one attention block given external residuals:
    per-row logsumexp ``lse`` and row correction ``delta`` [B, H, Tq],
    both computed over the GLOBAL softmax. This is the building block for
    distributed backward passes (ring attention accumulates these per KV
    rotation); shapes the kernels can't tile use the jnp twin.

    Default blocks come from the measured tables (whole-KV backward
    table, or flash2's past the compile limit — the whole-KV kernels do
    not COMPILE beyond :func:`_flash_max_seq`, see _select_impls);
    explicit block args always reach the kernel that runs."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, tq, d = q.shape
    tk = k.shape[2]
    long_seq = max(tq, tk) > _flash_max_seq()
    if block_q is None or block_k is None:
        dbq, dbk = _FLASH2_BLOCKS_BWD if long_seq else _kernel_blocks(tq)[1]
        block_q = block_q or dbq
        block_k = block_k or dbk
    bq = _fit_block(block_q, tq)
    bk = _fit_block(block_k, tk)
    if tq % bq or tk % bk or (causal and tq > tk):
        return _block_grads_reference(q, k, v, g, lse, delta, causal, scale)
    kernels = _flash2_backward_kernels if long_seq else _flash_backward_kernels
    return kernels(
        q, k, v, g,
        lse.reshape(b * h, tq), delta.reshape(b * h, tq),
        causal, scale, bq, bk, _interpret(),
    )


def _flash_backward(
    q, k, v, o, lse, g, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)

    delta = _bwd_delta(g, o, b, h, tq, d)
    return _flash_backward_kernels(
        q, k, v, g, lse, delta, causal, scale, block_q, block_k, interpret
    )


def _flash_backward_kernels(
    q, k, v, g, lse, delta, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """The two backward pallas calls; ``lse``/``delta`` are [B*H, Tq]."""
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    grp = _gqa_group(q, k)
    h_kv = h // grp

    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h_kv, tk, d)
    vf = v.reshape(b * h_kv, tk, d)
    gf = g.reshape(b * h, tq, d)
    # pallas layout: trailing singleton keeps the block sublane 8-aligned
    lse3 = lse[..., None]
    delta3 = delta[..., None]

    common = dict(causal=causal, scale=scale, q_offset=tk - tq)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_k=block_k, q_block=block_q, seq_k=tk, **common,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        grid=(b * h, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, g=grp: (i // g, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j, g=grp: (i // g, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3)

    # dk/dv come out at FULL q-head width (each program owns one q head's
    # contribution) and fold to the grouped width outside — the kernels
    # still never read a repeated K/V
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=block_q, k_block=block_k, seq_q=tq, **common,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        grid=(b * h, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, g=grp: (i // g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, g=grp: (i // g, j, 0)),
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tq, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tq, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3)

    dk, dv = _fold_dkv(
        dk.reshape(b, h, tk, d), dv.reshape(b, h, tk, d),
        b, h_kv, grp, tk, d,
    )
    return dq.reshape(b, h, tq, d), dk, dv


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_forward(
        q, k, v, causal, scale, block_q, block_k, _interpret()
    )
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_forward(
        q, k, v, causal, scale, block_q, block_k, _interpret()
    )
    return _name_residuals(q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v, o, lse = residuals
    if lse is None:  # ragged-shape fallback: differentiate the reference
        _, vjp = jax.vjp(
            lambda q, k, v: attention_reference(
                q, k, v, causal=causal, scale=scale
            ),
            q, k, v,
        )
        return vjp(g)
    return _flash_backward(
        q, k, v, o, lse, g, causal, scale, block_q, block_k, _interpret()
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
):
    """Forward-only ``(o, lse)`` with ``lse`` as [B, H, Tq] float32 —
    the primitive blockwise/ring merging builds on. Callers own
    differentiation (ring attention defines its own VJP from
    :func:`flash_block_grads`). Default blocks come from the measured
    tables (whole-KV kernel, or flash2 past its compile limit);
    explicit block args always reach the kernel that runs."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, tq, d = q.shape
    tk = k.shape[2]
    # resolve kernel + blocks FIRST so the ragged precheck validates the
    # exact blocks the kernel will run with
    long_seq = max(tq, tk) > _flash_max_seq()
    if block_q is None or block_k is None:
        dbq, dbk = _FLASH2_BLOCKS_FWD if long_seq else _kernel_blocks(tq)[0]
        block_q = block_q or dbq
        block_k = block_k or dbk
    bq = _fit_block(block_q, tq)
    bk = _fit_block(block_k, tk)
    if tq % bq or tk % bk or (causal and tq > tk):
        # ragged: take the reference path directly (one compute, with lse)
        return attention_reference_with_lse(
            q, k, v, causal=causal, scale=scale
        )
    forward = _flash2_forward if long_seq else _flash_forward
    # flash2 past the compile limit: the whole-KV kernel does not
    # COMPILE there (see _select_impls); same residual contract
    out, lse = forward(q, k, v, causal, scale, bq, bk, _interpret())
    return out, lse.reshape(b, h, tq)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """Flash attention; falls back to the reference on ragged shapes.

    Default blocks come from the measured per-seq table (``_BLOCK_TABLE``,
    v5e on-chip bq x bk sweep): e.g. bq=512 halves the forward at seq
    2048 vs the old fixed 128. Explicit block args win — including past
    the whole-KV compile limit, where they reach the flash2 kernels."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if max(q.shape[2], k.shape[2]) > _flash_max_seq():
        # whole-KV kernel does not compile past this length: serve the
        # same contract through the grid-pipelined kernels, filling any
        # unspecified block from flash2's own measured defaults
        fwd_blocks = (
            block_q or _FLASH2_BLOCKS_FWD[0],
            block_k or _FLASH2_BLOCKS_FWD[1],
        )
        bwd_blocks = (
            block_q or _FLASH2_BLOCKS_BWD[0],
            block_k or _FLASH2_BLOCKS_BWD[1],
        )
        return _auto(
            q, k, v, causal, scale, "flash2", "flash2",
            fwd_blocks, bwd_blocks,
        )
    if block_q is None or block_k is None:
        (fbq, fbk), _ = _kernel_blocks(q.shape[2])
        block_q = block_q or fbq
        block_k = block_k or fbk
    return _flash(q, k, v, causal, scale, block_q, block_k)


# -- measured dispatch ------------------------------------------------------
#
# Round-2 on-chip numbers (v5e bf16, [4,16,T,64], attention_tpu_r2.jsonl)
# showed the Pallas kernel LOSING to XLA's dense path forward at T<=2048
# (1.64 vs 0.97 ms at 1024, 6.18 vs 2.92 at 2048) while WINNING backward
# (flash bwd ~1.1/1.7 ms vs dense vjp ~1.8/6.8) and forward at 4096
# (25.0 vs 30.9). Shipping one implementation is a deoptimization
# somewhere; :func:`attention` instead composes the measured-fastest
# forward and backward independently — the dense path stays a candidate,
# so the dispatch is never slower than XLA by construction.

# (max_seq, impl) rows, first match wins; "whole" rows (when calibrated)
# route the entire op to jax's builtin TPU flash kernel instead of a
# fwd/bwd composition.
_DEFAULT_DISPATCH = {
    "fwd": ((2048, "ref"), (_INF, "flash")),
    "bwd": ((_INF, "flash"),),
    "whole": (),
}


# legal impl names per table section: a typo in a calibration artifact must
# fail fast at load, not silently reroute at the first attention() call
_VALID_IMPLS = {
    "fwd": {"ref", "flash", "flash2"},
    "bwd": {"ref", "flash", "flash2"},
    "whole": {"builtin", "comp"},
}


# calibration artifact shipped with the package (written by
# ``tools/attention_bench.py --calibrate`` on real hardware, copied in by
# the release flow) — the measured default for users who never set
# EDL_ATTN_DISPATCH
_PACKAGED_DISPATCH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "attention_dispatch.json"
)


def _load_table(path: str, base: dict) -> dict:
    """Parse a calibration artifact into a dispatch table (keys missing
    from the artifact keep ``base``'s rows), raising on any malformation
    (unknown impl, non-ascending bounds, bad JSON)."""
    import json

    with open(path) as f:
        raw = json.load(f)
    table = dict(base)
    for key in ("fwd", "bwd", "whole"):
        if key not in raw:
            continue
        rows = tuple(
            (_INF if m is None else m, impl) for m, impl in raw[key]
        )
        bad = [impl for _, impl in rows if impl not in _VALID_IMPLS[key]]
        if bad:
            raise ValueError(
                "unknown %s impl(s) %r (valid: %s)"
                % (key, bad, sorted(_VALID_IMPLS[key]))
            )
        bounds = [m for m, _ in rows]
        if any(not isinstance(m, (int, float)) for m in bounds):
            raise ValueError(
                "non-numeric %s bound in %r" % (key, raw[key])
            )
        if bounds != sorted(bounds):
            raise ValueError(
                "%s bounds not ascending: %r" % (key, raw[key])
            )
        table[key] = rows
    return table


@functools.lru_cache(maxsize=1)
def _dispatch_table() -> dict:
    """The active table, in priority order: a calibration artifact via
    ``EDL_ATTN_DISPATCH=<json>`` (``tools/attention_bench.py --calibrate``
    writes one: ``{"fwd": [[2048, "ref"], [null, "flash"]], ...}`` with
    ``null`` = no upper bound), else the calibration artifact packaged
    next to this module (``attention_dispatch.json``), else the
    hard-coded measured default.

    A malformed file or an unknown impl name falls back to the next
    source WITH a warning — never a silent routing change, never a lazy
    crash mid-train. An env artifact that omits a key inherits that
    key's rows from the packaged artifact (not the hard-coded default):
    each tier refines the one below it."""
    from edl_tpu.utils.log import get_logger

    logger = get_logger("ops.attention")
    base = _DEFAULT_DISPATCH
    base_name = "built-in measured default"
    if os.path.exists(_PACKAGED_DISPATCH):
        try:
            base = _load_table(_PACKAGED_DISPATCH, _DEFAULT_DISPATCH)
            base_name = "packaged calibration artifact"
        except (OSError, ValueError, TypeError) as exc:
            logger.warning(
                "packaged dispatch artifact %s unusable (%s); the "
                "built-in measured default table is the base",
                _PACKAGED_DISPATCH,
                exc,
            )
    path = os.environ.get("EDL_ATTN_DISPATCH", "")
    if path:
        try:
            return _load_table(path, base)
        except (OSError, ValueError, TypeError) as exc:
            logger.warning(
                "EDL_ATTN_DISPATCH=%s unusable (%s); using the %s table",
                path,
                exc,
                base_name,
            )
    return base


@functools.lru_cache(maxsize=1)
def _flash_max_seq() -> int:
    """Longest sequence the whole-KV flash kernel compiles for (v5e,
    jax 0.9; see _select_impls) — beyond it flash routes to the
    grid-pipelined flash2. ``EDL_FLASH_MAX_SEQ`` overrides; a malformed
    or non-positive value warns and keeps the measured default (same
    contract as EDL_ATTN_DISPATCH: never an import-time crash). Raising
    it past the measured limit re-exposes the whole-KV compile crash —
    only do so after a real-chip compile check on the target jax."""
    raw = os.environ.get("EDL_FLASH_MAX_SEQ", "4096")
    try:
        val = int(raw)
        if val <= 0:
            raise ValueError("must be positive")
        return val
    except ValueError:
        from edl_tpu.utils.log import get_logger

        get_logger("ops.attention").warning(
            "EDL_FLASH_MAX_SEQ=%r is not a positive int; using 4096", raw
        )
        return 4096


@functools.lru_cache(maxsize=1)
def _dense_score_bytes_limit() -> int:
    """Max fp32 score-matrix bytes before the dense forward is rerouted
    to flash regardless of the dispatch table. Default 2 GiB ≈ 1/8 of a
    v5e chip's 16 GiB HBM (scores are one of several live buffers and
    appear again transposed in the backward). ``EDL_ATTN_DENSE_LIMIT``
    overrides (bytes)."""
    import os

    return int(os.environ.get("EDL_ATTN_DENSE_LIMIT", 2 << 30))


def _lookup(rows, tq: int) -> str | None:
    for max_seq, impl in rows:
        if tq <= max_seq:
            return impl
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _auto(q, k, v, causal, scale, fwd_impl, bwd_impl,
          fwd_blocks=None, bwd_blocks=None):
    """``fwd_blocks``/``bwd_blocks`` are optional (bq, bk) overrides for
    the kernel impls (hashable tuples — they ride nondiff_argnums);
    ``None`` means the measured defaults for that impl."""
    return _auto_fwd(
        q, k, v, causal, scale, fwd_impl, bwd_impl, fwd_blocks, bwd_blocks
    )[0]


def _auto_fwd(q, k, v, causal, scale, fwd_impl, bwd_impl,
              fwd_blocks=None, bwd_blocks=None):
    if fwd_impl == "ref":
        out, lse = attention_reference_with_lse(
            q, k, v, causal=causal, scale=scale
        )
        b, h, tq, _ = q.shape
        # kernel layout, so a flash backward can consume a dense forward's
        # residuals (both are the logsumexp of the same scaled scores)
        lse = lse.reshape(b * h, tq)
    elif fwd_impl == "flash2":
        f2q, f2k = fwd_blocks or _FLASH2_BLOCKS_FWD
        out, lse = _flash2_forward(
            q, k, v, causal, scale, f2q, f2k, _interpret()
        )
    else:
        fbq, fbk = fwd_blocks or _kernel_blocks(q.shape[2])[0]
        out, lse = _flash_forward(
            q, k, v, causal, scale, fbq, fbk, _interpret()
        )
    return _name_residuals(q, k, v, out, lse)


def _name_residuals(q, k, v, out, lse):
    """Tag the vjp residuals with ``checkpoint_name`` so a ``jax.remat``
    policy can choose to SAVE the attention forward's products instead of
    re-running the kernel in the backward (``save_only_these_names``
    sees names inside a custom_vjp fwd). ``flash_out``/``flash_lse``
    are the expensive ones — saving them skips the whole forward kernel
    re-run under remat; ``flash_qkv`` additionally skips the projection
    recompute. See TransformerLM.remat_policy."""
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    if lse is not None:
        lse = checkpoint_name(lse, "flash_lse")
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return out, (q, k, v, out, lse)


def _auto_bwd(causal, scale, fwd_impl, bwd_impl, fwd_blocks, bwd_blocks,
              residuals, g):
    q, k, v, o, lse = residuals
    if bwd_impl in ("flash", "flash2") and lse is not None:
        tq, tk = q.shape[2], k.shape[2]
        # separate sweeps: _BLOCK_TABLE is the whole-KV kernel's,
        # _FLASH2_BLOCKS_BWD the grid-pipelined one's
        bbq, bbk = bwd_blocks or (
            _FLASH2_BLOCKS_BWD if bwd_impl == "flash2"
            else _kernel_blocks(tq)[1]
        )
        bq, bk = _fit_block(bbq, tq), _fit_block(bbk, tk)
        if not (tq % bq or tk % bk or (causal and tq > tk)):
            backward = (
                _flash2_backward if bwd_impl == "flash2" else _flash_backward
            )
            return backward(
                q, k, v, o, lse, g, causal, scale, bq, bk, _interpret()
            )
    _, vjp = jax.vjp(
        lambda q, k, v: attention_reference(
            q, k, v, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g)


_auto.defvjp(_auto_fwd, _auto_bwd)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Attention through the measured dispatch table — the default entry
    point for every model in the tree (TransformerLM, lm_bench, the LM
    examples). Forward and backward implementations are chosen
    independently per sequence length; off-TPU it is exactly the dense
    reference. ``flash_attention`` / ``attention_reference`` remain for
    callers that want a specific implementation."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if jax.default_backend() != "tpu":
        # native autodiff, NOT _auto("ref","ref"): the custom_vjp would
        # recompute the whole forward in every backward, where plain
        # differentiation reuses the saved activations
        return attention_reference(q, k, v, causal=causal, scale=scale)
    tq, tk = q.shape[2], k.shape[2]
    table = _dispatch_table()
    if (
        tq == tk
        and q.shape[1] == k.shape[1]  # builtin can't read grouped k/v
        and _lookup(table["whole"], tq) == "builtin"
    ):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _builtin_flash,
        )

        # tq == tk only: the builtin's causal mask is start-aligned, ours
        # end-aligned — the conventions agree exactly when lengths match
        return _builtin_flash(q, k, v, causal=causal, sm_scale=scale)
    fwd_impl, bwd_impl = _select_impls(
        table, q.shape[0], q.shape[1], tq, tk
    )
    return _auto(q, k, v, causal, scale, fwd_impl, bwd_impl)


def _select_impls(table, b: int, h: int, tq: int, tk: int):
    """Table lookup + memory guard -> ``(fwd_impl, bwd_impl)``.

    The table is calibrated at one [b, h] point, but the dense forward
    materializes the fp32 [Tq, Tk] score matrix per (batch, head) —
    O(b*h*T^2) HBM, recomputed under remat — while flash streams it.
    Beyond a bytes threshold the dense "win" trades a few ms for an
    OOM; route to flash there."""
    fwd_impl = _lookup(table["fwd"], tq) or "flash"
    bwd_impl = _lookup(table["bwd"], tq) or "flash"
    if b * h * tq * tk * 4 > _dense_score_bytes_limit():
        # dense bwd re-materializes the same score matrix via jax.vjp of
        # the reference forward — guard both directions
        fwd_impl = "flash" if fwd_impl == "ref" else fwd_impl
        bwd_impl = "flash" if bwd_impl == "ref" else bwd_impl
    if max(tq, tk) > _flash_max_seq():
        # measured on v5e (jax 0.9): the whole-KV-in-VMEM flash kernel
        # fails to COMPILE beyond 4096 (every block config crashed the
        # TPU compiler), while the grid-pipelined flash2 — constant VMEM
        # footprint by construction — compiles and runs at 8192+. This
        # is feasibility, not speed: the calibrated table can't express
        # "flash does not exist here".
        fwd_impl = "flash2" if fwd_impl == "flash" else fwd_impl
        bwd_impl = "flash2" if bwd_impl == "flash" else bwd_impl
    return fwd_impl, bwd_impl
