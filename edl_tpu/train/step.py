"""Train-step builders: jitted SPMD steps over a mesh.

The numeric heart the reference leaves to Paddle fleet
(``fleet.distributed_optimizer`` wrapping Momentum + NCCL allreduce,
reference train_with_fleet.py:326, 367-377) — here a single jitted function:
parameters live replicated (or fsdp-sharded) on the mesh, batches arrive
dp-sharded, and the gradient all-reduce is inserted by XLA from the
sharding algebra. bf16 compute happens inside the model (see models/);
parameters, BN statistics and optimizer state stay fp32 — the TPU-native
equivalent of the reference's AMP + loss-scaling flags
(train_with_fleet.py:68-73), no loss scaling needed for bf16.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import core, struct

from edl_tpu.obs import numerics as obs_numerics


class TrainState(struct.PyTreeNode):
    """Model + optimizer state (flax-style, with batch_stats for BN)."""

    step: jnp.ndarray
    apply_fn: Callable = struct.field(pytree_node=False)
    params: core.FrozenDict
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    opt_state: optax.OptState
    batch_stats: Optional[core.FrozenDict] = None

    def apply_gradients(self, grads, **updates) -> "TrainState":
        param_updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, param_updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **updates,
        )


def create_state(
    model,
    rng: jax.Array,
    sample_input,
    tx: optax.GradientTransformation,
    **init_kwargs,
) -> TrainState:
    variables = model.init(rng, sample_input, **init_kwargs)
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        apply_fn=model.apply,
        params=params,
        tx=tx,
        opt_state=tx.init(params),
        batch_stats=variables.get("batch_stats"),
    )


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, Dict]:
    one_hot = jax.nn.one_hot(labels, logits.shape[-1])
    loss = optax.softmax_cross_entropy(logits, one_hot).mean()
    accuracy = (jnp.argmax(logits, -1) == labels).mean()
    return loss, {"accuracy": accuracy}


def make_cross_entropy_loss(report_top_k: Optional[int] = None):
    """CE loss head with opt-in top-k accuracy reporting.

    ``report_top_k=5`` adds the acc5 the reference reports in every
    benchmark table (README.md:68-72, 144-147). Opt-in, NOT part of
    ``cross_entropy_loss``: LM heads route vocab-sized logits through the
    shared CE head every step, and a per-token top-k over the vocab is
    pure hot-path cost for a metric nothing reads there. Skipped when the
    class count is <= k (top-k of k classes is identically 1.0).
    """

    def head(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, Dict]:
        loss, metrics = cross_entropy_loss(logits, labels)
        if report_top_k and logits.shape[-1] > report_top_k:
            _, idx = jax.lax.top_k(logits, report_top_k)
            metrics = {
                **metrics,
                "top%d" % report_top_k: jnp.any(
                    idx == labels[..., None], axis=-1
                ).mean(),
            }
        return loss, metrics

    return head


def mse_loss(preds: jax.Array, targets: jax.Array) -> Tuple[jax.Array, Dict]:
    return jnp.mean((preds - targets) ** 2), {}


def make_kd_loss(alpha: float = 0.5, temperature: float = 1.0):
    """Knowledge-distillation loss head for ``make_train_step``.

    The batch target is ``(labels, teacher_logits)`` — the shape the
    distill pipeline yields (original fields + teacher predictions
    appended, reference distill_reader.py:351) and what the co-located
    fused step produces. Objective: ``(1-alpha)*CE(labels) +
    alpha*T^2*KL(teacher_T || student_T)`` (Hinton et al. 2015); the
    ``T^2`` keeps soft-target gradient magnitude independent of T.
    """

    def kd_loss(logits: jax.Array, y) -> Tuple[jax.Array, Dict]:
        labels, teacher_logits = y
        t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / temperature)
        s = jax.nn.log_softmax(logits / temperature)
        kl = jnp.sum(jnp.exp(t) * (t - s), axis=-1).mean()
        hard = optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(labels, logits.shape[-1])
        ).mean()
        loss = (1.0 - alpha) * hard + alpha * (temperature**2) * kl
        accuracy = (jnp.argmax(logits, -1) == labels).mean()
        return loss, {"accuracy": accuracy, "kd_kl": kl, "hard_ce": hard}

    return kd_loss


def make_train_step(
    loss_head: Callable[[jax.Array, jax.Array], Tuple[jax.Array, Dict]],
    apply_kwargs: Optional[Dict[str, Any]] = None,
    donate: bool = True,
    aux_losses: bool = False,
    numerics: bool = False,
):
    """Build ``step(state, (x, y)) -> (state, metrics)``.

    ``apply_kwargs`` are forwarded to the model (e.g. ``{"train": True}``
    for models with batch norm / dropout). ``aux_losses=True`` collects
    everything the model ``sow``-ed into the ``"losses"`` collection
    (e.g. MoE load-balancing terms) and adds it to the objective;
    the summed extra term is reported as ``metrics["aux_loss"]``.

    ``numerics=True`` fuses the numerics-plane bundle (obs/numerics)
    into the step: metrics gains a reserved ``METRICS_KEY`` entry of
    on-device scalars the caller must pop and hand to
    ``NumericsProbe.on_step`` (never aggregate it). When the batch is
    statically splittable — every leaf batched with the same even
    leading dim, no batch_stats, no aux_losses — and
    ``EDL_NUMERICS_GNS`` is not ``0``, the gradient is computed as the
    mean of two half-batch gradients instead of one full-batch pass:
    identical to the full-batch gradient for mean-reduced loss heads
    over equal halves, same FLOP count, one jit — and the two half
    norms feed the gradient-noise-scale estimator for free.
    """
    kwargs = dict(apply_kwargs or {})
    # env read at BUILD time, outside the traced step (jit purity): the
    # GNS knob shapes the trace like donate/aux_losses do
    want_gns = numerics and os.environ.get("EDL_NUMERICS_GNS", "1") != "0"

    def step(state: TrainState, batch):
        x, y = batch

        def loss_fn(params, bx, by):
            variables = {"params": params}
            mutable = []
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                mutable.append("batch_stats")
            if aux_losses:
                mutable.append("losses")
            if mutable:
                outputs, mutated = state.apply_fn(
                    variables, bx, mutable=mutable, **kwargs
                )
                new_stats = mutated.get("batch_stats")
            else:
                outputs = state.apply_fn(variables, bx, **kwargs)
                mutated, new_stats = {}, None
            loss, metrics = loss_head(outputs, by)
            if aux_losses:
                # always emit the metric so callers see a stable structure
                aux = sum(
                    (
                        jnp.sum(jnp.asarray(leaf))
                        for leaf in jax.tree.leaves(mutated.get("losses", {}))
                    ),
                    start=jnp.zeros((), jnp.float32),
                )
                loss = loss + aux
                metrics = {**metrics, "aux_loss": aux}
            return loss, (metrics, new_stats)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        # the half-batch split is decided STATICALLY at trace time from
        # concrete leaf shapes: no runtime branch reaches the schedule
        batch_size = None
        if want_gns and state.batch_stats is None and not aux_losses:
            leaves = jax.tree_util.tree_leaves(batch)
            dims = set()
            splittable = bool(leaves)
            for leaf in leaves:
                if hasattr(leaf, "shape") and getattr(leaf, "ndim", 0) >= 1:
                    dims.add(leaf.shape[0])
                else:
                    splittable = False  # an unbatched leaf cannot be halved
            if splittable and len(dims) == 1:
                b = dims.pop()
                if b >= 2 and b % 2 == 0:
                    batch_size = b
        halves = None
        if batch_size is not None:
            h = batch_size // 2
            x1, y1 = jax.tree_util.tree_map(lambda a: a[:h], (x, y))
            x2, y2 = jax.tree_util.tree_map(lambda a: a[h:], (x, y))
            (l1, (m1, _)), g1 = grad_fn(state.params, x1, y1)
            (l2, (m2, _)), g2 = grad_fn(state.params, x2, y2)
            loss = (l1 + l2) / 2.0
            grads = jax.tree_util.tree_map(lambda a, c: (a + c) / 2.0, g1, g2)
            metrics = jax.tree_util.tree_map(lambda a, c: (a + c) / 2.0, m1, m2)
            new_stats = None
            halves = (g1, g2)
        else:
            (loss, (metrics, new_stats)), grads = grad_fn(state.params, x, y)
        updates = {}
        if new_stats is not None:
            updates["batch_stats"] = new_stats
        new_state = state.apply_gradients(grads, **updates)
        metrics = {"loss": loss, **metrics}
        if numerics:
            metrics[obs_numerics.METRICS_KEY] = obs_numerics.device_bundle(
                loss, grads, state.params, new_state.params,
                halves=halves, batch=batch_size,
            )
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _masked_reduce(loss_head, outputs, y, mask, context: str):
    """Shared ragged-batch reduction for the masked train/eval steps.

    vmaps ``loss_head`` per row (enforcing the per-example-mean contract
    at trace time), then reduces loss and metrics over valid rows only.
    Returns ``(loss, metrics, n_valid)`` with ``n_valid`` the GLOBAL
    valid-row count — under SPMD the sums span every process's rows, so
    the quotient is the true global mean."""
    losses, metrics = jax.vmap(loss_head)(outputs, y)
    b = mask.shape[0]
    for name, v in [("loss", losses), *metrics.items()]:
        if v.shape != (b,):
            raise ValueError(
                "masked %s requires per-example loss heads: %r has "
                "shape %s under vmap, expected (%d,)"
                % (context, name, v.shape, b)
            )
    w = mask.astype(jnp.float32)
    n_valid = jnp.sum(w)
    denom = jnp.maximum(n_valid, 1.0)
    loss = jnp.sum(losses.astype(jnp.float32) * w) / denom
    out_metrics = {
        name: jnp.sum(v.astype(jnp.float32) * w) / denom
        for name, v in metrics.items()
    }
    return loss, out_metrics, n_valid


def make_masked_train_step(
    loss_head: Callable[[jax.Array, jax.Array], Tuple[jax.Array, Dict]],
    apply_kwargs: Optional[Dict[str, Any]] = None,
    donate: bool = True,
):
    """Sync-SGD step over a PADDED global batch: ``step(state, (x, y),
    mask) -> (state, metrics, n_valid)``.

    The ragged-tail TRAIN twin of :func:`make_masked_eval_step`, built
    for elastic data-layer feeds where workers pull *uneven* record
    shares (``data/dispatcher.py`` task stealing): every process steps
    at the same static shape — one compilation, one collective schedule
    — and contributes only its valid rows. The loss is the sum of
    per-example losses over valid rows divided by the GLOBAL valid
    count, so the gradient equals plain sync-SGD over exactly the valid
    rows; a worker whose share ran dry participates with an all-pad
    (zero-weight) batch instead of hanging the collective. Requires
    per-example-mean loss heads (same contract as the masked eval step,
    enforced at trace time).
    """
    kwargs = dict(apply_kwargs or {})

    def step(state: TrainState, batch, mask):
        x, y = batch

        def loss_fn(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                raise ValueError(
                    "masked train step does not support batch_stats "
                    "models: pad rows would pollute the running BN "
                    "statistics"
                )
            outputs = state.apply_fn(variables, x, **kwargs)
            loss, out_metrics, n_valid = _masked_reduce(
                loss_head, outputs, y, mask, "train"
            )
            return loss, (out_metrics, n_valid)

        (loss, (metrics, n_valid)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        new_state = state.apply_gradients(grads)
        return new_state, {"loss": loss, **metrics}, n_valid

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(
    loss_head: Callable[[jax.Array, jax.Array], Tuple[jax.Array, Dict]],
    apply_kwargs: Optional[Dict[str, Any]] = None,
):
    kwargs = dict(apply_kwargs or {})

    def step(state: TrainState, batch):
        x, y = batch
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        outputs = state.apply_fn(variables, x, **kwargs)
        loss, metrics = loss_head(outputs, y)
        return {"loss": loss, **metrics}

    # edl: donate-ok(eval re-reads the same TrainState every batch)
    return jax.jit(step)


def make_masked_eval_step(
    loss_head: Callable[[jax.Array, jax.Array], Tuple[jax.Array, Dict]],
    apply_kwargs: Optional[Dict[str, Any]] = None,
):
    """Eval step for a PADDED batch: ``step(state, batch, mask)``.

    Runs at the same static batch shape as every full batch — the ragged
    tail never changes shapes, so multi-process stages with sharded
    params see one uniform compilation and one uniform collective
    schedule. Pad rows are excluded by computing the loss head per row
    (``vmap``) and reducing under ``mask``; works for any head whose
    loss/metrics are per-example means (CE, top-k, KD, MSE). Returns
    ``(metrics, n_valid)`` with ``n_valid`` the GLOBAL valid-row count —
    the right weight for accumulating across batches.
    """
    kwargs = dict(apply_kwargs or {})

    def step(state: TrainState, batch, mask):
        x, y = batch
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        outputs = state.apply_fn(variables, x, **kwargs)
        # trace-time guard inside _masked_reduce: a head with batch-level
        # semantics (global top-k, batch-normalized reduction) yields
        # non-[batch] shapes under vmap and would silently disagree with
        # make_eval_step on the ragged tail
        loss, out_metrics, n_valid = _masked_reduce(
            loss_head, outputs, y, mask, "eval"
        )
        return {"loss": loss, **out_metrics}, n_valid

    # edl: donate-ok(eval re-reads the same TrainState every batch)
    return jax.jit(step)
