from edl_tpu.train.context import init, worker_barrier
from edl_tpu.train.step import (
    TrainState,
    create_state,
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
    mse_loss,
)

__all__ = [
    "init",
    "worker_barrier",
    "TrainState",
    "create_state",
    "make_train_step",
    "make_eval_step",
    "cross_entropy_loss",
    "mse_loss",
]
