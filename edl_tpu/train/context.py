"""Worker-side job bootstrap: join the distributed runtime, barrier.

This is the TPU-native seam the reference fills with Paddle fleet init:
where ``fleet.init(PaddleCloudRoleMaker)`` reads ``PADDLE_TRAINER_*`` env
set by the launcher and bootstraps NCCL (reference
example/collective/resnet50/train_with_fleet.py:377 + edl_process.py:54-62),
:func:`init` reads the ``EDL_*`` contract set by
:mod:`edl_tpu.launch.process` and drives ``jax.distributed.initialize``
with the published coordinator, so XLA collectives ride ICI/DCN.

Each elastic stage restarts worker processes, so ``init`` is always a
fresh-process bootstrap — the reference's stop-resume trick is what makes
coordinator handoff tractable (SURVEY §7 hard parts: the new stage's rank 0
hosts a fresh coordinator service on its own endpoint).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from edl_tpu.cluster.job_env import WorkerEnv
from edl_tpu.utils.exceptions import EdlBarrierError
from edl_tpu.utils.log import get_logger

logger = get_logger("train.context")

_env: Optional[WorkerEnv] = None
_distributed_up = False  # jax.distributed bootstrapped by a previous init()

from edl_tpu.cluster.contract import (  # shared with launch/launcher.py
    CLUSTER_SERVICE,
    DRAIN_SERVICE,
    DRAINED_EXIT,
    HEARTBEAT_SERVICE,
    HOT_RESTAGE_EXIT,
    HOTADOPT_SERVICE,
    PREEMPT_SERVICE,
)


def hot_restage_enabled() -> bool:
    """True when the job runs in hot-restage mode (``EDL_HOT_RESTAGE=1``):
    surviving workers adopt new stages IN-PROCESS instead of being killed
    and respawned — jax.distributed shutdown/initialize cycle, mesh
    rebuild, checkpoint restore — skipping the interpreter+import+compile
    cold start that dominates measured stop-resume downtime."""
    return os.environ.get("EDL_HOT_RESTAGE") == "1"


def enable_compilation_cache(path: str) -> None:
    """Point XLA's persistent compilation cache at ``path``.

    The resize-cost lever: stop-resume elasticity restarts every JAX
    process per stage, and without a persistent cache each incarnation
    recompiles the train step from scratch — 10s of seconds of the
    measured spawn→first-step downtime. With a job-scoped cache dir the
    SECOND visit to any world size loads the executable instead of
    compiling it (cache keys include topology, so each world size
    compiles once per host, ever). Thresholds drop to zero so even small
    test/CPU computations cache. Must run before the first computation;
    safe to call again with the same path.

    An unusable path (permissions, read-only fs) degrades to no cache with
    a warning instead of killing the worker: the cache is a performance
    lever, never a correctness requirement.
    """
    import jax

    try:
        # 0700 + ownership check: XLA deserializes executables from this
        # dir, so a pre-created world-writable path on a shared /tmp is a
        # code-injection surface, not just a perf artifact
        os.makedirs(path, mode=0o700, exist_ok=True)
        st = os.lstat(path)
        uid = os.getuid() if hasattr(os, "getuid") else st.st_uid
        if st.st_uid != uid or (st.st_mode & 0o022):
            logger.warning(
                "compilation cache dir %s not exclusively ours "
                "(owner uid %d, mode %o); continuing uncached",
                path,
                st.st_uid,
                st.st_mode & 0o777,
            )
            return
        probe = os.path.join(path, ".edl_probe_%d" % os.getpid())
        with open(probe, "w"):
            pass
        os.unlink(probe)
    except OSError as exc:
        logger.warning(
            "compilation cache dir %s unusable (%s); continuing uncached",
            path,
            exc,
        )
        return
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    if os.environ.get("EDL_CACHE_ALL_RANKS", "1") == "1":
        _enable_all_rank_cache_writes()
    # AOT resize plane (train/aot.py): topology-independent cache keys —
    # without them an entry the ladder compiles inside an N-process world
    # can never be hit by the N±1 incarnation it was compiled FOR — and
    # the hit/miss/write counters resize_bench and the monitor read.
    from edl_tpu.train import aot as _aot

    _aot.enable_portable_cache_keys()
    _aot.instrument_compilation_cache()


def _enable_all_rank_cache_writes() -> None:
    """Let EVERY process persist its compiled executables, not just rank 0.

    JAX hard-codes "only process 0 writes cache entries" to avoid write
    contention on shared filesystems like GCS — but cache keys include
    the process index, so in a multi-process job ranks >= 1 can never
    hit entries written by rank 0 and, with the default gate, nothing
    ever writes theirs: every elastic restage pays a full recompile on
    every non-zero rank, forever. On a host-local (or per-process-keyed)
    cache dir the contention rationale doesn't apply — distinct keys
    mean distinct files. This wraps ``jax._src.compiler._cache_write``
    to drop only that gate; if JAX's internals change shape, it logs
    and leaves the default behavior (``EDL_CACHE_ALL_RANKS=0`` opts
    out).
    """
    try:
        from jax._src import compiler as _compiler

        orig = getattr(_compiler, "_cache_write", None)
        if orig is None or getattr(orig, "_edl_all_ranks", False):
            if orig is None:
                logger.warning(
                    "jax._src.compiler._cache_write not found; cache "
                    "writes stay rank-0-only"
                )
            return

        real_distributed = _compiler.distributed

        class _GSView:
            """global_state view reporting process_id 0 (write-gate only)."""

            def __init__(self, gs):
                self._gs = gs

            process_id = 0

            def __getattr__(self, name):
                return getattr(self._gs, name)

        class _DistView:
            @property
            def global_state(self):
                return _GSView(real_distributed.global_state)

            def __getattr__(self, name):
                return getattr(real_distributed, name)

        import functools
        import types

        # A COPY of the function whose `distributed` global resolves to
        # the view: no runtime module mutation, no cross-thread effect on
        # other compiler-module code.
        patched = types.FunctionType(
            orig.__code__,
            {**orig.__globals__, "distributed": _DistView()},
            orig.__name__,
            orig.__defaults__,
            orig.__closure__,
        )
        patched = functools.wraps(orig)(patched)
        patched._edl_all_ranks = True
        _compiler._cache_write = patched
    except Exception as exc:  # private API drift: degrade, don't break
        logger.warning(
            "could not enable all-rank cache writes (%s); cache writes "
            "stay rank-0-only",
            exc,
        )


def _enable_cpu_collectives() -> None:
    """Arm Gloo CPU collectives before ``jax.distributed.initialize``.

    jax 0.4.37's CPU backend refuses to compile multi-process SPMD
    programs ("Multiprocess computations aren't implemented on the CPU
    backend") unless a collectives implementation is configured BEFORE
    the backend comes up — the default is none, so every multi-worker
    CPU world (the whole resize-bench/chaos rig) would die at its first
    cross-process compile. Guarded: older/newer jax without the option
    keeps its own default; ``EDL_CPU_COLLECTIVES`` overrides ("0" to
    skip, else the implementation name)."""
    choice = os.environ.get("EDL_CPU_COLLECTIVES", "gloo")
    if choice in ("0", "off", "none") or os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", choice)
    except Exception as exc:  # noqa: BLE001 — option drift: use jax's default
        logger.debug("cpu collectives %r not configurable: %s", choice, exc)


_cache_pulled = False


def _pull_cache_entries(env: WorkerEnv) -> None:
    """Bounded best-effort compile-cache pull at stage init (before the
    first jit): diff peer manifests, fetch entries any pod already
    compiled. Once per process — a hot restage re-runs init() but the
    cache dir it already pulled into is still warm; the standby shell
    sets ``EDL_CACHE_PULLED`` after its own (earlier, overlapped) pull
    for the same reason. Never raises, never blocks past the budget:
    the exchange is a perf lever, not a correctness gate."""
    global _cache_pulled
    if (
        _cache_pulled
        or warm_only()
        or os.environ.get("EDL_CACHE_PULLED") == "1"
        or os.environ.get("EDL_CACHE_EXCHANGE", "1") == "0"
        or not env.store_endpoint
        or not env.job_id
    ):
        return
    _cache_pulled = True
    try:
        from edl_tpu.train import aot as _aot

        _aot.pull_missing(
            env.compile_cache_dir,
            endpoint=env.store_endpoint,
            job_id=env.job_id,
            own_pod=env.pod_id,
        )
    except Exception as exc:  # noqa: BLE001
        logger.warning("compile-cache pull failed: %s", exc)


def warm_only() -> bool:
    """True inside a cache-warming shadow stage (``EDL_WARM_ONLY=1``,
    spawned by :mod:`edl_tpu.launch.warm`): the training script should run
    exactly one train step — enough to populate the persistent compile
    cache for this world size — then exit 0 without checkpoint writes or
    store traffic. ``ElasticTrainer.fit`` honors this automatically;
    hand-rolled loops check it themselves (tools/resize_bench_worker.py).
    """
    return os.environ.get("EDL_WARM_ONLY") == "1"


_boot_recorded = False


def _record_boot_span(obs_trace) -> None:
    """Once per process: a ``worker_boot`` restage-trace segment from the
    launcher's spawn stamp (``EDL_SPAWN_TS``) to now — the interpreter +
    import cold start the critical path must attribute, which no
    in-process code can otherwise observe. Skipped on hot restages (the
    process was not respawned, the stamp is stale)."""
    global _boot_recorded
    if _boot_recorded:
        return
    _boot_recorded = True
    raw = os.environ.get("EDL_SPAWN_TS", "")
    if not raw:
        return
    try:
        age = time.time() - float(raw)
    except ValueError:
        return
    if not 0.0 < age < 3600.0:
        return  # a clock step or an inherited stale stamp: drop it
    obs_trace.get_tracer().record(
        "worker_boot", time.monotonic() - age, age
    )


_obs_registered: Optional[tuple] = None


def _mount_obs(env: WorkerEnv) -> None:
    """Worker-side observability mount: /metrics + /healthz (gated on
    ``EDL_OBS_PORT``) plus endpoint registration in the job's obs
    keyspace so ``edl-top`` finds every worker. Re-registers when the
    (stage, rank) changes — a hot restage can move this process to a new
    rank. Never raises: obs must not break worker bootstrap."""
    global _obs_registered
    if warm_only():
        return  # shadow stages must not pollute the job's obs keyspace
    try:
        from edl_tpu.obs import http as obs_http

        server = obs_http.start_from_env(
            "worker",
            health_fn=lambda: {
                "rank": current_env().global_rank,
                "world": current_env().world_size,
                "stage": current_env().stage[:8],
            },
        )
        if server is None or not env.store_endpoint or not env.job_id:
            return
        key = (env.stage, env.global_rank)
        if _obs_registered == key:
            return
        from edl_tpu.store.client import connect_store

        client = connect_store(env.store_endpoint, timeout=2.0)
        try:
            obs_http.register_endpoint(
                client, env.job_id, "worker", "w%d" % env.global_rank,
                server.endpoint,
            )
        finally:
            client.close()
        _obs_registered = key
    except Exception as exc:  # noqa: BLE001
        logger.warning("worker obs mount failed: %s", exc)


def init(env: Optional[WorkerEnv] = None) -> WorkerEnv:
    """Join the job: returns the worker env; in multi-worker stages also
    initializes ``jax.distributed`` (rank 0's endpoint is the coordinator).

    Idempotent per process: user scripts call it for the env, and
    ``ElasticTrainer.fit`` calls it again — only the first call
    bootstraps ``jax.distributed`` (a second bootstrap is a hard error
    upstream). Stop-resume gives every stage a fresh process, so the
    guard can never carry across stages.
    """
    global _env, _distributed_up
    env = env or WorkerEnv()
    _env = env
    _mount_obs(env)
    if not warm_only():
        # goodput: from process start (stop-resume respawn) or in-process
        # re-init until training resumes, the wall-clock is restage cost
        from edl_tpu.obs import goodput as obs_goodput

        obs_goodput.enter("restage", cause="init")
        if env.stage:
            # distributed tracing: this worker's whole restage window —
            # boot, cache pull, jax.distributed join, restore, first jit
            # — stitches into the stage's restage trace (trace id derives
            # from the stage token, the key every participant shares).
            # Idempotent for the same stage; the step loop ends the op at
            # the first completed step.
            from edl_tpu.obs import trace as obs_trace

            obs_trace.begin_process_op(
                "restage", env.stage, rank=str(env.global_rank)
            )
            _record_boot_span(obs_trace)
    if env.compile_cache_dir:
        enable_compilation_cache(env.compile_cache_dir)
        _pull_cache_entries(env)
    if _distributed_up:
        return env
    if env.world_size > 1 and env.coordinator:
        import jax

        _enable_cpu_collectives()
        logger.info(
            "worker %d/%d joining stage %s (coordinator %s)",
            env.global_rank,
            env.world_size,
            env.stage[:8] or "-",
            env.coordinator,
        )
        try:
            # restage-trace segment: the distributed join can dominate a
            # restage (it barriers on the slowest joiner's cold start)
            from edl_tpu.obs import trace as obs_trace

            with obs_trace.child_span(
                "dist_init", world=str(env.world_size)
            ):
                jax.distributed.initialize(
                    coordinator_address=env.coordinator,
                    num_processes=env.world_size,
                    process_id=env.global_rank,
                )
            _distributed_up = True
        except RuntimeError as exc:
            if "must be called before" in str(exc):
                raise RuntimeError(
                    "jax was initialised before joining the multi-worker "
                    "stage: build device arrays only AFTER init()/fit() "
                    "(e.g. pass numpy arrays as ElasticTrainer sample_input)"
                ) from exc
            raise
    return env


def current_env() -> WorkerEnv:
    return _env if _env is not None else WorkerEnv()


# -- hot restage (in-process stage adoption) --------------------------------


class StageMonitor:
    """Worker-side watch of the job's drain token and published cluster.

    The stop-resume contract learns about stage changes by being killed;
    a hot-restage worker learns by watching the same store keys the
    launcher does: a drain-token bump ≠ my stage sets ``restage_pending``
    (checked between train steps — never inside compiled code), and
    ``wait_for_my_stage`` then blocks until the leader publishes the new
    generation. ``mark_adopted`` reports success back to the launcher,
    which kills+respawns any worker that misses its adoption deadline
    (the dirty fallback: a peer death can leave this process wedged in a
    collective, where only the runtime's own abort or the launcher's
    kill can recover it)."""

    def __init__(self, env: WorkerEnv) -> None:
        from edl_tpu.discovery.registry import Registry
        from edl_tpu.store.client import connect_store

        self._client = connect_store(env.store_endpoint, timeout=10.0)
        self._registry = Registry(self._client, env.job_id)
        self._stage = env.stage
        self._changed = threading.Event()
        self._drain = self._registry.watch_service(
            DRAIN_SERVICE, on_change=self._on_change
        )
        self._cluster = self._registry.watch_service(
            CLUSTER_SERVICE, on_change=self._on_change
        )
        self._on_change()

    def _token(self) -> str:
        meta = self._drain.snapshot().get("token")
        return meta.value.decode() if meta else ""

    def _on_change(self, _snapshot=None) -> None:
        token = self._token()
        if token and token != self._stage:
            self._changed.set()

    @property
    def restage_pending(self) -> bool:
        return self._changed.is_set()

    def wait_for_my_stage(self, pod_id: str, timeout: float = 20.0):
        """Block until the CURRENT token's generation is published with
        ``pod_id`` in it; returns the Cluster, or None when this pod is
        excluded from the generation or nothing converges in time."""
        from edl_tpu.cluster.model import Cluster

        deadline = time.time() + timeout
        while time.time() < deadline:
            token = self._token()
            meta = self._cluster.snapshot().get("current")
            if token and meta is not None:
                cluster = Cluster.from_json(meta.value)
                if cluster.stage == token:
                    return cluster if cluster.get_pod(pod_id) else None
            time.sleep(0.05)
        return None

    def arm(self, stage: str) -> None:
        """Reset for a newly adopted stage (and immediately re-flag if the
        token has already moved past it)."""
        self._stage = stage
        self._changed.clear()
        self._on_change()

    def mark_adopted(self, pod_id: str, rank_in_pod: int, stage: str) -> None:
        self._registry.set_permanent(
            HOTADOPT_SERVICE, "%s.%d" % (pod_id, rank_in_pod), stage.encode()
        )

    def close(self) -> None:
        for watch in (self._drain, self._cluster):
            try:
                watch.cancel()
            except Exception:
                pass
        self._client.close()


# -- health plane (graceful drain + progress heartbeat) ----------------------


class HealthMonitor:
    """Worker-side half of the health plane.

    Watches the job's ``preempt/{pod_id}`` key — published by the launcher
    when it receives an advance preemption notice (SIGTERM/SIGUSR1), or by
    an operator directly — and exposes the drain deadline so the training
    loop can take an emergency checkpoint between steps and exit with
    ``DRAINED_EXIT``. Also publishes the per-step progress heartbeat
    (``heartbeat/{pod_id}.{rank_in_pod}``) the launcher-side straggler
    watchdog reads; publication is throttled to ``EDL_HEARTBEAT_EVERY``
    seconds (default 1.0) and strictly fire-and-forget — a sick store must
    never stall a training step.

    Notice delivery is belt-and-suspenders: the watch is the fast path,
    and :meth:`heartbeat` re-reads the pod's own preempt key about once a
    second — a watch event lost to a reconnect race costs at most that
    second, never the whole drain window.
    """

    _POLL_EVERY = 1.0  # direct preempt-key read cadence (watch-miss floor)

    def __init__(self, env: WorkerEnv, min_interval: Optional[float] = None) -> None:
        from edl_tpu.discovery.registry import Registry
        from edl_tpu.store.client import connect_store

        self._env = env
        self._client = connect_store(env.store_endpoint, timeout=2.0)
        self._registry = Registry(self._client, env.job_id or "job")
        self._hb_key = "/%s/%s/%s.%d" % (
            env.job_id, HEARTBEAT_SERVICE, env.pod_id, env.rank_in_pod,
        )
        self._preempt_key = "/%s/%s/%s" % (
            env.job_id, PREEMPT_SERVICE, env.pod_id,
        )
        if min_interval is None:
            min_interval = float(os.environ.get("EDL_HEARTBEAT_EVERY", "1.0"))
        self._min_interval = min_interval
        self._last_pub = 0.0
        self._last_poll = 0.0
        self._backoff_until = 0.0
        self._deadline: Optional[float] = None
        self._noticed = threading.Event()
        self._watch = self._registry.watch_service(
            PREEMPT_SERVICE, on_change=self._on_change
        )
        self._on_change(self._watch.snapshot())

    @property
    def store_client(self):
        """The health plane's store client, shared with sibling
        best-effort planes (the numerics digest exchange) so one worker
        holds one store connection, not one per observer."""
        return self._client

    def _apply_notice(self, value: bytes) -> None:
        import json as _json

        try:
            payload = _json.loads(value)
            deadline = float(payload.get("deadline", 0)) or None
        except (ValueError, TypeError):
            deadline = None
        self._deadline = deadline
        self._noticed.set()

    def _on_change(self, snapshot=None) -> None:
        if snapshot is None:
            snapshot = self._watch.snapshot()
        meta = snapshot.get(self._env.pod_id)
        if meta is None:
            return
        self._apply_notice(meta.value)

    @property
    def drain_notice(self) -> bool:
        """True once this pod has been told to drain."""
        return self._noticed.is_set()

    @property
    def drain_deadline(self) -> Optional[float]:
        """Wall-clock deadline of the notice (None = no notice, or one
        without a parseable deadline — drain immediately, best effort)."""
        return self._deadline

    def drain_budget_left(self, floor: float = 0.5) -> float:
        """Seconds the emergency checkpoint may still spend."""
        if self._deadline is None:
            return floor
        return max(floor, self._deadline - time.time())

    def heartbeat(self, step: int, dt: float = 0.0) -> None:
        """Publish step progress (throttled, fire-and-forget)."""
        now = time.time()
        if now < self._backoff_until:
            return
        if not self._noticed.is_set() and now - self._last_poll >= self._POLL_EVERY:
            # watch-miss insurance: one direct read of the preempt key
            self._last_poll = now
            try:
                raw = self._client.get(self._preempt_key)
                if raw is not None:
                    self._apply_notice(raw)
            except Exception as exc:  # noqa: BLE001 — never stall a step
                self._backoff_until = now + 5.0
                logger.debug("preempt poll failed: %s", exc)
                return
        if now - self._last_pub < self._min_interval:
            return
        import json as _json

        try:
            self._client.put(
                self._hb_key,
                _json.dumps(
                    {
                        "step": int(step),
                        "ts": now,
                        "dt": round(float(dt), 4),
                        "stage": self._env.stage,
                    }
                ).encode(),
            )
            self._last_pub = now
        except Exception as exc:  # noqa: BLE001 — never stall a train step
            self._backoff_until = now + 5.0
            logger.debug("heartbeat publish failed: %s", exc)

    def record_drained(self, step: int) -> None:
        """Best-effort 'drained' telemetry event + final heartbeat, written
        right before the worker exits with ``DRAINED_EXIT``."""
        from edl_tpu.obs import events as obs_events
        from edl_tpu.obs import goodput as obs_goodput
        from edl_tpu.obs import trace as obs_trace
        from edl_tpu.utils import telemetry

        obs_goodput.enter("drain", cause="preempt")
        # the drain op's closing segment (zero-duration anchor): marks
        # the trace complete for edl-trace even when no emergency save
        # ran (multi-pod partial drains skip it — Orbax is collective)
        obs_trace.get_tracer().record(
            "drained", time.monotonic(), 0.0, step=str(step)
        )
        obs_events.record(
            "drained", fsync=True, step=step,
            pod=self._env.pod_id, rank=self._env.global_rank,
        )
        self._min_interval = 0.0  # the exit heartbeat must not be throttled
        self._backoff_until = 0.0
        self.heartbeat(step)
        telemetry.record_event(
            self._client, self._env.job_id, self._env.stage, "drained",
            "w%d" % self._env.global_rank,
        )

    def close(self) -> None:
        try:
            self._watch.cancel()
        except Exception:  # noqa: BLE001
            pass
        self._client.close()


def reinit_for_stage(cluster, pod_id: str, rank_in_pod: int) -> WorkerEnv:
    """Adopt ``cluster``'s stage in-process: recompute this worker's env
    from the published generation, tear down the old distributed runtime
    and backends, and re-run :func:`init`.

    After this returns, every jax Array and compiled function from the
    previous stage is dead weight — callers rebuild mesh/state/steps from
    scratch (the persistent compile cache makes the re-jit a load, not a
    compile). Raises on anything dirty; callers translate that into a
    ``HOT_RESTAGE_EXIT`` respawn request.
    """
    global _distributed_up
    from edl_tpu.obs import goodput as obs_goodput

    obs_goodput.enter("restage", cause="hot_restage")
    pod = cluster.get_pod(pod_id)
    if pod is None:
        raise RuntimeError("pod %s not in stage %s" % (pod_id, cluster.stage))
    worker = next(
        (w for w in pod.workers if w.rank_in_pod == rank_in_pod), None
    )
    if worker is None:
        raise RuntimeError(
            "rank_in_pod %d not in pod %s for stage %s"
            % (rank_in_pod, pod_id, cluster.stage)
        )
    os.environ.update(
        {
            "EDL_STAGE": cluster.stage,
            "EDL_WORKER_RANK": str(worker.global_rank),
            "EDL_NUM_WORKERS": str(cluster.world_size),
            "EDL_COORDINATOR": cluster.coordinator,
            "EDL_WORKER_ENDPOINTS": ",".join(cluster.worker_endpoints()),
        }
    )

    import jax

    if _distributed_up:
        jax.distributed.shutdown()
        _distributed_up = False
    jax.clear_caches()
    # backends hold the old distributed client; initialize() refuses to
    # run while they exist. Private API by necessity — guarded so drift
    # degrades to the respawn fallback instead of undefined behavior.
    from jax._src import xla_bridge

    xla_bridge._clear_backends()
    if xla_bridge.backends_are_initialized():
        raise RuntimeError("jax backends survived _clear_backends()")
    new_env = WorkerEnv()
    logger.info(
        "hot restage: adopting stage %s as rank %d/%d (coordinator %s)",
        new_env.stage[:8],
        new_env.global_rank,
        new_env.world_size,
        new_env.coordinator,
    )
    return init(new_env)


_barrier_rounds: dict = {}


def worker_barrier(name: str, timeout: float = 600.0, ttl: float = 10.0) -> None:
    """Control-plane barrier across all workers of the current stage.

    Capability parity with the reference's leader-hosted ``Barrier`` RPC
    (python/edl/utils/pod_server.py:63, pod_client.py:37), built on the
    store instead of a dedicated server: every worker registers
    ``barrier/{stage}:{name}#{round}/{rank}`` (leased) and waits until all
    ``world_size`` ranks are present. The per-process round counter makes
    the same barrier name reusable back-to-back: keys from round N (left
    to lease expiry) can never satisfy round N+1. All ranks hit barriers
    in program order, so counters agree across processes; a restarted
    worker resets to round 0 together with everyone else because restarts
    only happen at stage changes and the stage is part of the key.
    """
    env = current_env()
    if env.world_size <= 1 or not env.store_endpoint:
        return
    from edl_tpu.discovery.registry import Registry
    from edl_tpu.store.client import connect_store

    round_key = (env.stage, name)
    seq = _barrier_rounds.get(round_key, 0)
    _barrier_rounds[round_key] = seq + 1
    service = "barrier/%s:%s#%d" % (env.stage or "static", name, seq)
    client = connect_store(env.store_endpoint, timeout=min(timeout, 30.0))
    try:
        registry = Registry(client, env.job_id or "job")
        # push-based wait: the store watch wakes us on every membership
        # change (the reference polls its leader barrier RPC at ~3 Hz,
        # pod_client.py:37; early rounds here polled at 20 Hz)
        full = threading.Event()
        seen = [0]

        def on_change(snapshot):
            seen[0] = len(snapshot)
            if len(snapshot) >= env.world_size:
                full.set()

        watch = registry.watch_service(service, on_change=on_change)
        reg = registry.register(service, str(env.global_rank), b"1", ttl=ttl)
        try:
            if not full.wait(timeout):
                raise EdlBarrierError(
                    "barrier %r timed out: %d/%d workers"
                    % (name, seen[0], env.world_size)
                )
        finally:
            watch.cancel()
            reg.stop(delete=False)  # leave the key; lease expiry cleans up
    finally:
        client.close()
