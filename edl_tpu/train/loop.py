"""ElasticTrainer: the one-call elastic training loop.

The reference sketches this user-facing API but never built it — its
aspirational test (python/edl/tests/unittests/test_train.py:28-67) wants a
``PaddleState`` with ``register_adjust_function`` and per-batch notify,
and its flagship example hand-assembles the same ~80-line loop in every
script (example/collective/resnet50/train_with_fleet.py:367-570: fleet
init → build → load checkpoint → epoch loop → rank-0 save). Here the loop
is a reusable class over the edl_tpu primitives:

  - joins the elastic job from the launcher env (``train.init``),
  - builds the device mesh and dp-shards the input pipeline
    (``batched`` + ``prefetch_to_device`` keep HBM fed),
  - resolves hyper-parameter adjustments for the CURRENT world size
    (``AdjustRegistry``, e.g. linear-scaled lr) before building the
    optimizer — the elastic-resize contract,
  - restores the latest checkpoint (Orbax reshards across topology
    changes) and saves per epoch, rank-0 logs,
  - barriers the stage so all workers enter compiled collectives
    together.

A stage change (resize) is handled the stop-resume way: the launcher
kills and respawns the process, and ``fit`` naturally resumes from the
last checkpoint under the new world size with re-resolved
hyper-parameters.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import numpy as np
import optax

from edl_tpu.checkpoint import AdjustRegistry, CheckpointManager, TrainStatus
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import goodput as obs_goodput
from edl_tpu.obs import memory as obs_memory
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import numerics as obs_numerics
from edl_tpu.obs import profile as obs_profile
from edl_tpu.obs import trace as obs_trace

_M_STEP_SECONDS = obs_metrics.histogram(
    "edl_train_step_seconds",
    "train step wall time, dispatch-to-dispatch (includes input wait)",
)
_M_STEPS = obs_metrics.counter(
    "edl_train_steps_total", "train steps dispatched"
)
_M_EPOCHS = obs_metrics.counter(
    "edl_train_epochs_total", "epochs completed"
)
_M_FIRST_STEP = obs_metrics.gauge(
    "edl_train_first_step_seconds",
    "first step of the stage (jit trace + compile or cache load)",
)
from edl_tpu.data import batched, prefetch_to_device
from edl_tpu.parallel import (
    batch_sharding,
    device_put_global,
    make_mesh,
    replicated,
    shard_batch,
    shard_params_fsdp,
)
from edl_tpu.train.context import init, warm_only, worker_barrier
from edl_tpu.train.step import TrainState, create_state, make_train_step

DataFn = Callable[[int], Iterable]  # epoch -> records or ready batches


_M_DRAINS = obs_metrics.counter(
    "edl_train_drains_total", "graceful worker drains (preemption notices honored)"
)


class _RestageRequested(Exception):
    """Raised out of the step loop when the stage this process runs under
    has been superseded (hot-restage mode only)."""


class ElasticTrainer:
    """Drive an elastic SPMD training job end to end.

    ``optimizer`` is either an ``optax.GradientTransformation`` or a
    factory ``overrides_dict -> tx`` — the factory form is what makes
    hyper-parameter adjustment on resize work (it is called with the
    merged ``AdjustRegistry`` output for the current world size, e.g.
    ``{"lr": 0.4}``).

    ``data_fn(epoch)`` returns the epoch's data: raw records when
    ``batch_size`` is set (they get packed into fixed-shape batches,
    ragged tail dropped), or ready ``(x, y)`` host batches otherwise.
    Epoch-seeded generators give the reference's ``pass_id_as_seed``
    deterministic-resume contract (train_with_fleet.py:458-464).

    ``sample_input`` should be a NUMPY array (or shape-dtype struct): a
    jax device array built before ``fit()`` initialises the backend,
    which breaks ``jax.distributed`` bootstrap in multi-worker stages.
    """

    def __init__(
        self,
        model,
        optimizer,
        loss: Callable,
        sample_input,
        mesh_axes: Optional[Dict[str, int]] = None,
        fsdp: bool = False,
        ckpt_dir: Optional[str] = None,
        adjusts: Optional[AdjustRegistry] = None,
        apply_kwargs: Optional[Dict[str, Any]] = None,
        init_kwargs: Optional[Dict[str, Any]] = None,
        batch_size: Optional[int] = None,
        batch_axis: str = "dp",
        async_save: bool = False,
        prefetch_depth: int = 2,
        seed: int = 0,
        log: bool = True,
    ) -> None:
        self._model = model
        self._optimizer = optimizer
        self._loss = loss
        self._sample_input = sample_input
        self._mesh_axes = mesh_axes
        self._fsdp = fsdp
        self._ckpt_dir = ckpt_dir
        self._adjusts = adjusts
        self._apply_kwargs = apply_kwargs
        self._init_kwargs = dict(init_kwargs or {})
        self._batch_size = batch_size
        self._batch_axis = batch_axis
        self._async_save = async_save
        self._depth = prefetch_depth
        self._seed = seed
        self._log = log
        self._eval_step = None  # jitted once, reused across evaluate() calls
        self._masked_eval_step = None

    def _make_tx(self, overrides: Dict[str, Any]):
        if isinstance(self._optimizer, optax.GradientTransformation):
            return self._optimizer
        return self._optimizer(overrides)

    def fit(
        self,
        data_fn: DataFn,
        epochs: int,
        on_epoch_end: Optional[Callable[[int, Dict], None]] = None,
    ) -> TrainState:
        """Train to ``epochs``; under ``EDL_HOT_RESTAGE=1`` this also
        survives elastic stage changes WITHOUT a process restart: a
        drain-token bump raises out of the step loop, the distributed
        runtime is torn down and re-initialized for the new generation,
        and the loop re-enters from the last checkpoint — the same
        resume contract as stop-resume, minus the interpreter, import,
        and compile-cache cold start. Anything dirty during the
        handover exits with ``HOT_RESTAGE_EXIT`` so the launcher falls
        back to a cold respawn."""
        from edl_tpu.train import context as ctx

        if not ctx.hot_restage_enabled():
            return self._fit_stage(data_fn, epochs, on_epoch_end, None)
        env = init()
        monitor = (
            ctx.StageMonitor(env)
            if env.store_endpoint and not warm_only()
            else None
        )
        try:
            while True:
                try:
                    return self._fit_stage(
                        data_fn, epochs, on_epoch_end, monitor
                    )
                except _RestageRequested:
                    self._hot_restage(monitor)
        finally:
            if monitor is not None:
                monitor.close()

    def _hot_restage(self, monitor) -> None:
        """Adopt the new generation in-process, or exit for a respawn."""
        import sys as _sys

        from edl_tpu.train import context as ctx

        env = ctx.current_env()
        grace = float(os.environ.get("EDL_HOT_GRACE", "20"))
        try:
            cluster = monitor.wait_for_my_stage(env.pod_id, timeout=grace)
            if cluster is None:
                raise RuntimeError(
                    "no published generation includes this pod"
                )
            # confirm the handoff BEFORE jax.distributed re-init: the
            # launcher's deadline exists to catch workers wedged in dead
            # collectives, which can never reach this line — while the
            # re-init barrier legitimately blocks on slow joiners (a cold
            # pod's interpreter+import start) for longer than any sane
            # wedge deadline. initialize() has its own timeout; a failure
            # there exits via HOT_RESTAGE_EXIT below.
            monitor.mark_adopted(env.pod_id, env.rank_in_pod, cluster.stage)
            new_env = ctx.reinit_for_stage(
                cluster, env.pod_id, env.rank_in_pod
            )
            monitor.arm(new_env.stage)
            # jitted eval steps compiled under the old backend are dead
            self._eval_step = None
            self._masked_eval_step = None
        except Exception as exc:
            print(
                "elastic-trainer: hot restage failed (%s); requesting "
                "respawn" % exc,
                file=_sys.stderr,
            )
            _sys.exit(ctx.HOT_RESTAGE_EXIT)

    def _drain_exit(self, health, mngr, state, epoch: int, step: int, env):
        """Honor a preemption notice between steps: emergency checkpoint
        within the notice's budget (best effort — an unfinished save is
        quarantined by restore-side fallback), record the drain, and leave
        with the clean ``DRAINED_EXIT`` code the launcher expects."""
        from edl_tpu.train import context as ctx

        # drain operation trace (keyed by pod id, same derivation as the
        # launcher's root): the emergency save and drained records below
        # stitch under the pod's drain op
        obs_trace.begin_process_op("drain", env.pod_id)
        obs_goodput.enter("drain", cause="preempt")
        budget = health.drain_budget_left()
        if mngr is not None and env.world_size == 1:
            # Orbax saves are COLLECTIVE across jax.distributed processes:
            # a single draining pod of a multi-pod stage cannot checkpoint
            # alone (its peers are not draining and will never join the
            # save), so the partial-drain case keeps the last periodic
            # version and relies on the proactive restage. A full-job
            # notice drains every pod, which stop-resume handles pod by
            # pod; the single-process world (and the chaos trainee, which
            # saves per-rank) get the exact bounded-lost-work snapshot.
            # epoch-1: this epoch is NOT complete — resume replays it from
            # the start with the (further-advanced) emergency state, the
            # same contract as being killed mid-epoch, minus the lost steps
            status = TrainStatus(
                epoch=epoch - 1,
                step=int(state.step),
                world_size=env.world_size,
                meta={"emergency": True, "mid_epoch": epoch},
            )
            mngr.emergency_save(state, status, budget)
        elif mngr is not None:
            # the multi-pod partial-drain gap, closed: this pod cannot
            # checkpoint alone (the save is collective), but it CAN make
            # the checkpoints it already holds survive its departure —
            # a peer replica push is per-pod and non-collective
            # (checkpoint/replicate.py; no-op without a local tier)
            mngr.emergency_replicate(budget)
        _M_DRAINS.inc()
        health.record_drained(step)
        if env.is_rank0 and self._log:
            print(
                "elastic-trainer: preemption notice honored at epoch %d "
                "step %d (budget %.1fs); exiting drained" % (epoch, step, budget)
            )
        sys.exit(ctx.DRAINED_EXIT)

    def _fit_stage(
        self,
        data_fn: DataFn,
        epochs: int,
        on_epoch_end: Optional[Callable[[int, Dict], None]],
        monitor,
    ) -> TrainState:
        from edl_tpu.train import context as ctx

        env = init()
        t_setup = time.monotonic()  # train_setup trace segment starts here
        mesh = make_mesh(self._mesh_axes)
        # cache-warming shadow stage: compile + one step, no checkpoint
        # manager at all (a warm stage must never touch the job's ckpt dir)
        warm = warm_only()
        mngr = (
            CheckpointManager(self._ckpt_dir, async_save=self._async_save)
            if self._ckpt_dir and not warm
            else None
        )
        # health plane: drain-notice watch + step heartbeats. Best-effort
        # by design — a job without a store (or a store that is down right
        # now) trains exactly as before, it just cannot drain gracefully.
        health = None
        if env.store_endpoint and env.job_id and not warm:
            try:
                health = ctx.HealthMonitor(env)
            except Exception as exc:  # noqa: BLE001
                print(
                    "elastic-trainer: health monitor unavailable (%s); "
                    "continuing without graceful drain" % exc,
                    file=sys.stderr,
                )
        step_telemetry: Optional[obs_profile.StepTelemetry] = None
        capture: Optional[obs_profile.CaptureController] = None
        ladder = None  # AOT resize ladder, armed after the first step
        # memory plane: compile-time plan + census/watermarks + OOM
        # forensics, per stage (a warm shadow stage compiles and exits —
        # its plan would be the same executable's, published twice)
        mem_plane: Optional[obs_memory.MemoryPlane] = None
        if not warm:
            try:
                mem_plane = obs_memory.MemoryPlane(
                    stage=env.stage, rank=env.global_rank,
                    client=(
                        health.store_client if health is not None else None
                    ),
                    job_id=env.job_id or "",
                    expect_donation=True,  # make_train_step donates state
                )
            except Exception as exc:  # noqa: BLE001 — memory plane is telemetry
                print(
                    "elastic-trainer: memory plane unavailable (%s); "
                    "continuing without it" % exc,
                    file=sys.stderr,
                )
        # numerics plane: fused bundle + throttled host export. The warm
        # shadow stage never publishes (its two steps are compile bait,
        # not training). Shares the health plane's store client for the
        # cross-replica digest exchange when one exists.
        probe = None
        if not warm and obs_numerics.enabled():
            probe = obs_numerics.NumericsProbe(
                rank=env.global_rank,
                client=health.store_client if health is not None else None,
                job_id=env.job_id or "",
            )
        try:
            with mesh:
                # peek the checkpointed status FIRST: adjust callbacks are
                # contractually given (restored_status_or_None, world) so
                # e.g. epoch-aware lr schedules survive stop-resume
                peeked = mngr.read_status() if mngr is not None else None
                overrides = (
                    self._adjusts.resolve(peeked, env.world_size)
                    if self._adjusts is not None
                    else {}
                )
                state = create_state(
                    self._model,
                    jax.random.PRNGKey(self._seed),
                    self._sample_input,
                    self._make_tx(overrides),
                    **self._init_kwargs,
                )
                # every leaf must land on the mesh (a leaf left committed
                # to device 0 — e.g. the .step scalar — clashes with
                # mesh-placed args at jit time and checkpoint restore)
                rep = replicated(mesh)
                if self._fsdp:
                    # params/opt_state shard DIRECTLY from host: replicating
                    # first would put the full model on every device — the
                    # memory peak fsdp exists to avoid
                    state = state.replace(
                        params=shard_params_fsdp(mesh, state.params),
                        opt_state=shard_params_fsdp(mesh, state.opt_state),
                        step=device_put_global(state.step, rep),
                        # tree.map over None is None: no-op without stats
                        batch_stats=jax.tree.map(
                            lambda x: device_put_global(x, rep),
                            state.batch_stats,
                        ),
                    )
                else:
                    state = jax.tree.map(
                        lambda x: device_put_global(x, rep), state
                    )
                start_epoch = 0
                if mngr is not None:
                    state, status = mngr.restore(state)
                    if status and probe is not None:
                        # arm the resume-continuity check against the
                        # checkpoint's stamped numerics fingerprint
                        probe.expect((status.meta or {}).get("numerics"))
                    if status:
                        start_epoch = status.next_epoch()
                        if env.is_rank0 and self._log:
                            print(
                                "elastic-trainer: resumed at epoch %d "
                                "(world=%d%s)"
                                % (
                                    start_epoch,
                                    env.world_size,
                                    "".join(
                                        ", %s=%s" % kv
                                        for kv in sorted(overrides.items())
                                    ),
                                )
                            )
                # the warm shadow stage compiles WITH the bundle fused
                # (enabled(), not probe) — its cache entry must be the
                # computation the real stage will look up
                step = make_train_step(
                    self._loss, self._apply_kwargs,
                    numerics=obs_numerics.enabled(),
                )
                sharding = batch_sharding(mesh, self._batch_axis)
                worker_barrier("elastic-trainer-start")
                # restage-trace segment: state build + restore + stage
                # barrier (the restore nests under it as its own span)
                obs_trace.get_tracer().record(
                    "train_setup", t_setup, time.monotonic() - t_setup
                )
                # goodput: everything from here until the first completed
                # step is attributed to compile (jit trace + XLA compile,
                # or persistent-cache load)
                obs_goodput.enter("compile", cause="first_step")
                # profiling plane: windowed MFU/roofline/HBM gauges
                # (armed with the step's cost analysis after the first
                # step) + store-driven on-demand jax.profiler windows.
                # EDL_PROFILE_DIR keeps its historical meaning — ONE
                # env-armed window for the whole fit (the reference
                # profiles batches 100-105, train_with_fleet.py:524-534)
                # — now riding the same controller as store requests.
                step_telemetry = obs_profile.StepTelemetry()
                if not warm:
                    try:
                        capture = obs_profile.CaptureController(
                            env, telemetry=step_telemetry
                        )
                        profile_dir = os.environ.get("EDL_PROFILE_DIR")
                        if profile_dir:
                            capture.arm_local(
                                profile_dir, start_after=10, steps=5
                            )
                    except Exception as exc:  # noqa: BLE001 — profiling is best-effort
                        print(
                            "elastic-trainer: capture plane unavailable "
                            "(%s); continuing without it" % exc,
                            file=sys.stderr,
                        )
                tracer = obs_trace.get_tracer()
                first_step_done = False
                steps_done = 0  # stage-cumulative, drives the heartbeat
                last_flight = 0.0  # throttled flight-recorder step marker
                for epoch in range(start_epoch, epochs):
                    metrics: Dict[str, Any] = {}
                    batches = data_fn(epoch)
                    if self._batch_size is not None:
                        batches = (
                            b
                            for b, _ in batched(
                                batches, self._batch_size, drop_remainder=True
                            )
                        )
                    step_idx = 0
                    t_epoch = time.monotonic()
                    t_prev = t_epoch
                    # explicit iterator: the time blocked in next() is the
                    # input pipeline's fault (data_wait), the dispatch
                    # interval after it is the step's (train) — the split
                    # the goodput ledger exists to make
                    batch_iter = iter(prefetch_to_device(
                        batches, depth=self._depth, sharding=sharding
                    ))
                    while True:
                        if first_step_done:
                            obs_goodput.enter("data_wait")
                        try:
                            device_batch = next(batch_iter)
                        except StopIteration:
                            break
                        if first_step_done:
                            obs_goodput.enter("train")
                        if health is not None and health.drain_notice:
                            # drain beats restage: this pod is leaving the
                            # job, not joining the next generation
                            self._drain_exit(
                                health, mngr, state, epoch, steps_done, env
                            )
                        if monitor is not None and monitor.restage_pending:
                            # between steps, never inside compiled code;
                            # the in-flight step's work is simply dropped
                            # (same loss as a stop-resume kill)
                            raise _RestageRequested()
                        if mem_plane is not None:
                            # RESOURCE_EXHAUSTED leaves a forensics
                            # bundle (census + device memory profile +
                            # the plan + an fsync'd `oom` instant)
                            # before propagating into drain/restage
                            with mem_plane.oom_guard(
                                step=steps_done, epoch=epoch
                            ):
                                state, metrics = step(state, device_batch)
                        else:
                            state, metrics = step(state, device_batch)
                        # pop BEFORE any aggregation/printing: the bundle
                        # is device arrays for the probe, not a scalar
                        # metric. No host sync here — the probe fetches
                        # on its own throttle.
                        bundle = metrics.pop(obs_numerics.METRICS_KEY, None)
                        if probe is not None:
                            probe.on_step(steps_done, bundle)
                        # dispatch-to-dispatch wall time: jax dispatch is
                        # async, but the state dependency chain makes the
                        # steady-state interval track real step time
                        t_now = time.monotonic()
                        dt = t_now - t_prev
                        _M_STEP_SECONDS.observe(dt)
                        _M_STEPS.inc()
                        if not first_step_done:
                            # restage trace: the first completed step is
                            # the operation's closing segment (jit trace
                            # + compile or cache load), recorded while
                            # the op context is still live so it stitches
                            # — then the restage window ends
                            tracer.record(
                                "first_step", t_prev, dt, epoch=epoch
                            )
                            obs_trace.end_process_op()
                        tracer.record(
                            "train_step", t_prev, dt,
                            epoch=epoch, step=step_idx,
                        )
                        if not first_step_done:
                            # the stage's cold-start cost: jit trace +
                            # compile (or persistent-cache load)
                            _M_FIRST_STEP.set(dt)
                            first_step_done = True
                            obs_goodput.enter("train", cause="first_step")
                            # arm the MFU/roofline gauges with XLA's own
                            # cost analysis for this step shape — a jax
                            # trace, no second XLA compile (the compiled
                            # executable already sits in the jit cache)
                            step_telemetry.set_cost(
                                obs_profile.step_cost(
                                    step, state, device_batch
                                )
                            )
                            if mem_plane is not None:
                                # compile-time memory plan for THIS
                                # stage's executable: a jax trace + a
                                # jit/persistent-cache hit, no second
                                # XLA compile (mirrors step_cost)
                                mem_plane.harvest(
                                    step, state, device_batch,
                                    world=env.world_size,
                                )
                            # steady state reached: speculatively compile
                            # the N±1/N±2 neighbor worlds into the
                            # persistent cache on a low-priority thread
                            # (train/aot.py) so the NEXT resize re-jits
                            # from a cache load instead of a compile
                            if not warm and env.compile_cache_dir:
                                ladder = self._start_ladder(
                                    env, step, state, device_batch,
                                    mem_plane=mem_plane,
                                )
                        step_telemetry.observe_step(dt)
                        if mem_plane is not None:
                            # throttled census + watermark sample
                            # (EDL_MEM_CENSUS_EVERY; metadata only,
                            # never a host sync on the step path)
                            mem_plane.on_step(steps_done)
                        t_prev = t_now
                        step_idx += 1
                        steps_done += 1
                        if t_now - last_flight >= 1.0:
                            # throttled black-box marker: bounds a killed
                            # worker's open goodput interval to <= 1 s
                            last_flight = t_now
                            obs_events.record(
                                "train_heartbeat", step=steps_done, epoch=epoch
                            )
                        if health is not None:
                            health.heartbeat(steps_done, dt)
                        if capture is not None:
                            # store-driven profiler window state machine;
                            # the sync makes the closing trace contain
                            # the device work it claims to
                            capture.on_step(
                                sync=lambda m=metrics: jax.block_until_ready(m)
                            )
                        if warm and step_idx >= 2:
                            # two steps, not one: step 1 caches the
                            # host-placed-state compile, step 2 the
                            # steady-state (mesh-sharded inputs) one
                            jax.block_until_ready(metrics)
                            if env.is_rank0 and self._log:
                                print(
                                    "warm-only stage (world=%d): step "
                                    "compiled and cached; exiting"
                                    % env.world_size
                                )
                            sys.exit(0)
                    if first_step_done:
                        # the epoch-end device sync below is step work,
                        # not input wait
                        obs_goodput.enter("train")
                    if metrics:
                        jax.block_until_ready(metrics)
                    if env.is_rank0 and self._log and metrics:
                        print(
                            "epoch %d %s"
                            % (
                                epoch,
                                " ".join(
                                    "%s %.4f" % (k, float(np.asarray(v)))
                                    for k, v in sorted(metrics.items())
                                    if np.asarray(v).ndim == 0
                                ),
                            )
                        )
                    if not metrics and env.is_rank0 and self._log:
                        print(
                            "epoch %d produced no full batches "
                            "(fewer than batch_size records?)" % epoch
                        )
                    _M_EPOCHS.inc()
                    tracer.record(
                        "train_epoch", t_epoch,
                        time.monotonic() - t_epoch,
                        epoch=epoch, steps=step_idx,
                    )
                    if on_epoch_end is not None:
                        on_epoch_end(epoch, metrics)
                    if mngr is not None:
                        mngr.save(
                            state,
                            TrainStatus(epoch=epoch, step=int(state.step)),
                        )
                if mngr is not None:
                    mngr.wait()
                obs_goodput.close(cause="complete")
                return state
        finally:
            if probe is not None:
                probe.close()
            if ladder is not None:
                ladder.close()
            if capture is not None:
                capture.close()
            if mem_plane is not None:
                mem_plane.close()
            if step_telemetry is not None:
                step_telemetry.close()
            if health is not None:
                health.close()
            if mngr is not None:
                mngr.close()

    def _start_ladder(self, env, step, state, device_batch, mem_plane=None):
        """Arm the AOT resize ladder for this stage (best-effort)."""
        from edl_tpu.train import aot

        if not aot.aot_enabled():
            return None
        try:
            worlds = aot.neighbor_worlds(
                env.world_size, env.nproc_per_node,
                env.min_nodes, env.max_nodes,
            )
            if not worlds:
                return None
            compile_for = aot.make_neighbor_compiler(
                step, state, device_batch,
                mesh_axes=self._mesh_axes, batch_axis=self._batch_axis,
                devices_per_proc=aot.devices_per_process(env),
                # each rung's executable was compiled anyway — its
                # memory plan is free, and publishing it is what lets
                # the scale plane fit-gate THAT world before choosing it
                on_compiled=(
                    mem_plane.harvest_rung if mem_plane is not None else None
                ),
            )
            return aot.AotLadder(env, compile_for, worlds=worlds).start()
        except Exception as exc:  # noqa: BLE001 — speculation must not gate training
            print(
                "elastic-trainer: aot ladder unavailable (%s); resizes "
                "will compile on arrival" % exc,
                file=sys.stderr,
            )
            return None

    def evaluate(self, state: TrainState, data_fn: Callable[[], Iterable]):
        """Run one evaluation pass and return sample-weighted mean metrics.

        ``data_fn()`` yields records (when ``batch_size`` is set) or
        ready host batches, like ``fit``'s per-epoch data. The final
        ragged batch is NOT dropped: ``batched``'s pad+mask keeps shapes
        static and the metric mean weights each batch by its valid-row
        count, so eval covers every record exactly once — the part the
        reference leaves to Paddle's test loop (train_with_fleet.py's
        test pass).
        """
        from edl_tpu.train.step import make_eval_step, make_masked_eval_step

        mesh = make_mesh(self._mesh_axes)
        if self._eval_step is None:
            self._eval_step = make_eval_step(self._loss, self._apply_kwargs)
            self._masked_eval_step = make_masked_eval_step(
                self._loss, self._apply_kwargs
            )
        eval_step = self._eval_step
        masked_eval_step = self._masked_eval_step
        pending = []  # (device metrics, n_valid): fetched once at the end

        with mesh:
            sharding = batch_sharding(mesh, self._batch_axis)
            batches = data_fn()
            if self._batch_size is not None:
                pairs = batched(batches, self._batch_size)
            else:
                pairs = ((b, None) for b in batches)
            # full batches ride the same overlapped transfer pipeline as
            # fit; the (single, final) ragged batch is set aside
            ragged = []

            def full_batches():
                for b, m in pairs:
                    if m is not None and not m.all():
                        ragged.append((b, m))
                    else:
                        yield b

            for placed in prefetch_to_device(
                full_batches(), depth=self._depth, sharding=sharding
            ):
                n = float(jax.tree.leaves(placed)[0].shape[0])
                # no host sync inside the loop: batch N+1 dispatches while
                # batch N computes; everything is fetched once at the end
                pending.append((eval_step(state, placed), n))

            for host_batch, mask in ragged:
                # padded tail stays at the STATIC batch shape (no per-process
                # shape divergence under sharded params); pad rows are
                # excluded by the mask inside the jitted step, and the
                # batch's weight is the global valid-row count it returns
                placed = shard_batch(mesh, host_batch, self._batch_axis)
                mask_dev = shard_batch(mesh, np.asarray(mask), self._batch_axis)
                pending.append(masked_eval_step(state, placed, mask_dev))
        totals: Dict[str, float] = {}
        weight = 0.0
        for metrics, n_valid in pending:
            n_valid = float(np.asarray(n_valid))
            for name, v in metrics.items():
                arr = np.asarray(v)  # blocks; all compute already queued
                if arr.ndim == 0:
                    totals[name] = totals.get(name, 0.0) + float(arr) * n_valid
            weight += n_valid
        return {name: v / max(weight, 1.0) for name, v in totals.items()}
