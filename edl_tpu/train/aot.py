"""AOT resize ladder + cluster-shared compile-cache exchange.

The dominant restage cost on TPU is XLA recompilation for the new mesh
shape (12-28 s per resize, bench_results/resize_tpu_r4b.json) — yet the
elastic window makes every resize target enumerable, and pjit binds the
mesh at *call site*, not trace time: nothing stops a live worker from
compiling the N±1/N±2 executables while training runs. Three pieces make
the post-resize re-jit a cache load instead of a compile:

**Portable cache keys** (:func:`enable_portable_cache_keys`). JAX's
persistent-cache key hashes the *backend topology* (process count,
global device set), so an entry compiled inside an N-process world can
never be hit by an (N-1)-process incarnation even when the program, the
compile options and the program's own devices are identical — measured:
the same world-1 step gets a different key in every topology it is
compiled from. The patch re-keys the accelerator-config component to
the *program's* device kinds + platform (JAX's own documented fallback
for backends without serializable topology), making the key a pure
function of (HLO, compile options, device kinds, platform) — and strips
the per-fusion-autotune-cache *path* (derived from the local cache dir,
so it differs per pod) from the compile options before they are hashed,
the same way JAX strips xla_gpu_cuda_data_dir. Proven on
the CPU rig: a world-1 entry compiled from inside a 2-process world is
hit byte-for-byte by a real world-1 job. Scoped like the all-rank-write
patch in train/context.py: guarded against private-API drift, env
opt-out, CPU-only by default (``EDL_CACHE_PORTABLE_KEYS=all`` extends
it to TPU — queued for on-chip confirmation in run_tpu_suite round 7;
topology-keyed entries are the conservative default where real ICI
topology differences could matter).

**The AOT ladder** (:class:`AotLadder`). Once a stage reaches steady
state (first step done), a low-priority background thread compiles the
train step for the anticipated neighbor world sizes — pods ±1 and ±2
inside the elastic window, nearest first — via
``jit(...).lower(shapes).compile()`` with ``ShapeDtypeStruct`` avals
scaled to each target world, populating the persistent cache every
incarnation already points at. Only *shrink* shapes are compilable
in-process (a grow mesh needs devices this process cannot see; those
ride the launcher's shadow-stage warmer and the exchange below), and
only by a worker whose local device sits in the target sub-mesh. Sizes
are claimed through the store (leased while compiling, permanent
``done:`` on success — warm.py's dedupe idiom) so co-hosted pods never
compile the same shape twice. Ladder time is attributed to the new
``aot_compile`` goodput state on its own flight-recorder lane
(component ``aot``) — never the ``train`` lane.

**The cache exchange** (:class:`CacheExchange` / :func:`pull_missing`).
Portable keys make entries *host-portable*, so no pod ever needs to
compile what any peer already paid for: each launcher publishes a
sha256 digest manifest of its local cache entries under
``compile_cache/{pod}`` and serves entry bytes over the wire protocol;
a restaging or newly joined pod diffs manifests against its local dir
and pulls what is missing — from ``train.init()`` (bounded, before the
first jit) and from the standby shell's activation path (where the
pull overlaps the control-plane convergence window). A corrupted or
dropped pull degrades to a normal compile, never a wedged worker:
every entry is digest-verified before an atomic rename into the cache
dir, and the whole pull is deadline-bounded and exception-contained
(chaos point ``store.cache.exchange`` drills exactly this).

Observability: ``edl_train_aot_compiles_total{outcome}``,
``edl_train_cache_exchange_bytes_total{dir}``,
``edl_train_compile_cache_events_total{kind}`` (hit/miss/write, from
the instrumented persistent-cache read/write seam),
``edl_train_restage_compile_seconds`` (real compile time paid between a
cache miss and its write — the number speculation exists to zero), and
``aot``/``exchange`` flight records so edl-timeline shows the
speculation paying off.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.log import get_logger

logger = get_logger("train.aot")

AOT_SERVICE = "aot"                    # store claims: aot/{world}
MANIFEST_SERVICE = "compile_cache"     # store manifests: compile_cache/{pod}

_FP_COMPILE = _fault_point(
    "train.aot.compile",
    "one ladder compile: delay (slow speculative compile) or drop "
    "(compile fails; the ladder counts it and moves on)",
)
_FP_EXCHANGE = _fault_point(
    "store.cache.exchange",
    "one pulled cache entry: corrupt (digest mismatch -> entry skipped, "
    "resize degrades to a normal compile), delay, drop (peer unreachable "
    "mid-pull)",
)

class RungUnavailable(ValueError):
    """A ladder rung that can never compile here — a permanent property
    of the model/window (e.g. a sharded dim not divisible over the
    neighbor mesh), distinct from a real compile failure."""


_M_AOT = obs_metrics.counter(
    "edl_train_aot_compiles_total",
    "speculative ladder compiles, by outcome (ok/failed/skipped_grow/"
    "skipped_nonlocal/skipped_claimed/skipped_indivisible)",
)
_M_XCHG_BYTES = obs_metrics.counter(
    "edl_train_cache_exchange_bytes_total",
    "compile-cache entry bytes moved between pods, by dir (rx/tx)",
)
_M_CACHE_EVENTS = obs_metrics.counter(
    "edl_train_compile_cache_events_total",
    "persistent compile-cache events at the jit seam, by kind "
    "(hit/miss/write)",
)
_M_RESTAGE_COMPILE = obs_metrics.histogram(
    "edl_train_restage_compile_seconds",
    "real XLA compile time paid per cache miss (miss-to-write interval); "
    "zero entries here after a resize means the speculation paid off",
)

# the ladder's OWN speculative compiles go through the same instrumented
# persistent-cache seam as a restage jit — but a speculation in progress
# is the opposite of a missed one: its miss->write interval must not
# feed the restage histogram (the restage-compile-regression rule would
# fire exactly when the ladder works as designed) nor the hit/miss
# ledger resize_bench reads ("compile events = 0" means the FOREGROUND
# jit paid nothing)
_in_ladder = threading.local()


# -- portable cache keys ------------------------------------------------------

def enable_portable_cache_keys() -> bool:
    """Make persistent-cache keys topology-independent (see module doc).

    Idempotent; returns True when the patch is (already) active. Opt out
    with ``EDL_CACHE_PORTABLE_KEYS=0``; ``=all`` extends beyond CPU.
    Guarded like ``_enable_all_rank_cache_writes``: private-API drift
    degrades to the stock topology-keyed behavior with a warning.
    """
    mode = os.environ.get("EDL_CACHE_PORTABLE_KEYS", "cpu").lower()
    if mode in ("0", "off", "none"):
        return False
    try:
        from jax._src import cache_key as _ck

        current = getattr(_ck, "_hash_accelerator_config", None)
        if current is None:
            logger.warning(
                "jax._src.cache_key._hash_accelerator_config not found; "
                "cache keys stay topology-bound"
            )
            return False
        if not getattr(current, "_edl_portable", False):
            hash_devices = _ck._hash_devices
            hash_platform = _ck._hash_platform

            def _portable(hash_obj, accelerators, backend, _orig=current):
                platform = getattr(backend, "platform", "")
                if mode != "all" and platform != "cpu":
                    return _orig(hash_obj, accelerators, backend)
                # the program's own devices + platform — JAX's documented
                # fallback for backends without serializable topology. The
                # device COUNT and KINDS still key (a 4-device program never
                # collides with a 2-device one); what no longer keys is the
                # process topology the compile happened to run inside.
                hash_devices(hash_obj, accelerators)
                hash_platform(hash_obj, backend)

            _portable._edl_portable = True
            _ck._hash_accelerator_config = _portable

        # second host-bound leak: jax arms XLA's per-fusion autotune
        # cache UNDER the compilation cache dir, and the resulting
        # debug-option (a local filesystem path) rides the serialized
        # compile options into the key — so two pods with different
        # cache dir paths can never share an entry. Clear it for keying
        # exactly like jax clears xla_gpu_cuda_data_dir (a path is not a
        # compile input); the real option still reaches the compiler.
        orig_opts = getattr(_ck, "_hash_serialized_compile_options", None)
        if orig_opts is not None and not getattr(
            orig_opts, "_edl_portable", False
        ):
            import copy as _copy

            def _portable_opts(
                hash_obj, compile_options_obj, *args, _orig=orig_opts, **kw
            ):
                try:
                    stripped = _copy.deepcopy(compile_options_obj)
                    dbg = stripped.executable_build_options.debug_options
                    for field in (
                        "xla_gpu_per_fusion_autotune_cache_dir",
                        "xla_gpu_experimental_autotune_cache_dir",
                    ):
                        if getattr(dbg, field, ""):
                            setattr(dbg, field, "")
                    compile_options_obj = stripped
                except Exception:  # noqa: BLE001 — proto drift: hash as-is
                    pass
                return _orig(hash_obj, compile_options_obj, *args, **kw)

            _portable_opts._edl_portable = True
            _ck._hash_serialized_compile_options = _portable_opts
        return True
    except Exception as exc:  # noqa: BLE001 — private API drift: degrade
        logger.warning(
            "could not enable portable cache keys (%s); resize ladder "
            "entries will only be hit by same-topology incarnations", exc
        )
        return False


# -- cache hit/miss instrumentation -------------------------------------------

_miss_started: Dict[str, float] = {}  # cache_key -> monotonic at miss
_miss_lock = threading.Lock()


def instrument_compilation_cache() -> bool:
    """Count persistent-cache hits/misses/writes at the jit seam.

    Wraps ``compilation_cache.get_executable_and_time`` /
    ``put_executable_and_time`` so resize_bench and the monitor can tell
    "cache load" from "real compile" without parsing logs, and times the
    miss→write interval into ``edl_train_restage_compile_seconds`` (the
    actual XLA compile the miss forced). Idempotent, drift-guarded,
    opt-out with ``EDL_CACHE_EVENTS=0``.
    """
    if os.environ.get("EDL_CACHE_EVENTS", "1") == "0":
        return False
    try:
        from jax._src import compilation_cache as _cc

        orig_get = _cc.get_executable_and_time
        orig_put = _cc.put_executable_and_time
        if getattr(orig_get, "_edl_events", False):
            return True

        def get_wrapper(cache_key, compile_options, backend,
                        _orig=orig_get, **kw):
            if getattr(_in_ladder, "active", False):
                return _orig(cache_key, compile_options, backend, **kw)
            executable, compile_time = _orig(
                cache_key, compile_options, backend, **kw
            )
            if executable is None:
                _M_CACHE_EVENTS.inc(kind="miss")
                with _miss_lock:
                    _miss_started[cache_key] = time.monotonic()
            else:
                _M_CACHE_EVENTS.inc(kind="hit")
            return executable, compile_time

        def put_wrapper(cache_key, module_name, executable, backend,
                        compile_time, _orig=orig_put, **kw):
            if getattr(_in_ladder, "active", False):
                return _orig(
                    cache_key, module_name, executable, backend,
                    compile_time, **kw
                )
            with _miss_lock:
                t0 = _miss_started.pop(cache_key, None)
            if t0 is not None:
                _M_RESTAGE_COMPILE.observe(time.monotonic() - t0)
                if os.environ.get("EDL_CACHE_EVENTS_DEBUG") == "1":
                    # names the executables speculation failed to cover
                    logger.info(
                        "cache miss compiled: %s (%.2fs)",
                        module_name, time.monotonic() - t0,
                    )
            _M_CACHE_EVENTS.inc(kind="write")
            return _orig(
                cache_key, module_name, executable, backend, compile_time,
                **kw
            )

        get_wrapper._edl_events = True
        put_wrapper._edl_events = True
        _cc.get_executable_and_time = get_wrapper
        _cc.put_executable_and_time = put_wrapper
        return True
    except Exception as exc:  # noqa: BLE001
        logger.warning("cache-event instrumentation unavailable: %s", exc)
        return False


def cache_event_counts() -> Dict[str, int]:
    """Snapshot of {hit, miss, write} counts this process has seen."""
    return {
        kind: int(_M_CACHE_EVENTS.value(kind=kind))
        for kind in ("hit", "miss", "write")
    }


# -- the AOT ladder -----------------------------------------------------------

def aot_enabled() -> bool:
    """Ladder gate: on by default wherever a compile cache is armed;
    ``EDL_AOT=0`` (resize_bench ``--no-aot``) disables."""
    return os.environ.get("EDL_AOT", "1") != "0"


def neighbor_worlds(
    world: int, nproc: int, min_nodes: int, max_nodes: int,
    depth: int = 2,
) -> List[int]:
    """The ladder's target world sizes: pods ±1..±depth inside the
    elastic window, nearest rung first, shrink before grow at equal
    distance (shrinks are what this process can compile)."""
    nproc = max(1, nproc)
    pods = world // nproc
    if pods * nproc != world:
        return []
    out: List[int] = []
    for k in range(1, depth + 1):
        for target in (pods - k, pods + k):
            if min_nodes <= target <= max_nodes and target != pods:
                w = target * nproc
                if w not in out:
                    out.append(w)
    return out


def devices_per_process(env=None) -> int:
    """Devices each process of ANY incarnation of this job owns.

    ``world`` everywhere in this module counts PROCESSES (that is the
    store-claim key and the metric label), but meshes are built from
    devices — and on real TPU a process owns several chips, so the
    world->mesh mapping must scale by this factor or the ladder compiles
    executables for meshes no real stage ever runs. The launcher's
    contract is homogeneous (``num_devices = local_device_count //
    nproc``): ``EDL_DEVICES_PER_PROC`` (the CPU rigs pin it to 1) wins;
    otherwise it is derived from the live backend — global devices over
    the current process count."""
    override = os.environ.get("EDL_DEVICES_PER_PROC")
    if override:
        try:
            return max(1, int(override))
        except ValueError:
            pass
    import jax

    if env is None:
        # no world to divide by: the process's OWN device count is the
        # per-process figure (dividing the global set by a defaulted
        # world=1 would claim every device in the job is ours)
        return max(1, len(jax.local_devices()))
    world = max(1, int(getattr(env, "world_size", 1) or 1))
    return max(1, len(jax.devices()) // world)


class AotLadder:
    """Background speculative compiler for neighbor world sizes.

    ``compile_for(world)`` is supplied by the integration site (it
    closes over the jitted step and the live avals — see
    :func:`make_neighbor_compiler`); the ladder owns everything else:
    rung enumeration, local-device feasibility, store claims, the
    low-priority thread, pacing, metrics, the ``aot_compile`` goodput
    lane and the ``train.aot.compile`` fault point.

    ``close()`` is cooperative: a compile in flight cannot be
    interrupted, so close joins briefly and abandons the daemon thread —
    a hot restage that tears the backends down under a running compile
    turns it into a counted failure, never a crash.
    """

    def __init__(
        self,
        env,
        compile_for: Callable[[int], None],
        worlds: Optional[Sequence[int]] = None,
        client=None,
        delay: Optional[float] = None,
    ) -> None:
        self._env = env
        self._compile_for = compile_for
        if worlds is None:
            worlds = neighbor_worlds(
                env.world_size, env.nproc_per_node,
                env.min_nodes, env.max_nodes,
            )
        self._worlds = list(worlds)
        # guards _client create/close and the _compile_for release: the
        # ladder thread lazily dials the store / drops the closure while
        # close() runs on the training thread
        self._mu = threading.Lock()
        self._client = client  # edl: guarded-by(self._mu)
        self._owns_client = client is None
        # let the live stage settle before stealing cycles from it (the
        # same measured lesson as warm.py's EDL_PREWARM_DELAY)
        if delay is None:
            delay = float(os.environ.get("EDL_AOT_DELAY", "1.0"))
        self._delay = delay
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compiled: List[int] = []
        # separate ledger + flight lane: the MAIN thread keeps owning the
        # process's train/data_wait attribution; ladder seconds land in
        # aot_compile on a component="aot" lane and can never displace
        # the train lane in the job-level sweep (priority is below every
        # foreground state)
        from edl_tpu.obs import goodput as obs_goodput

        self._ledger = obs_goodput.GoodputLedger(component="aot")

    def start(self) -> "AotLadder":
        if self._thread is None and self._worlds:
            self._thread = threading.Thread(
                target=self._run, name="edl-aot-ladder", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        # drop the (state, batch) closure even when the thread was
        # abandoned mid-compile: a hot restage keeps this process (and
        # its HBM) alive long after the ladder is gone
        with self._mu:
            self._compile_for = None
            owns, client = self._owns_client, self._client
            if owns:
                self._client = None
        self._ledger.close(cause="ladder_close")
        if owns and client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass

    # -- store claims (warm.py's dedupe idiom) -----------------------------

    def _store(self):
        with self._mu:
            client = self._client
        endpoint = getattr(self._env, "store_endpoint", "")
        if client is not None or not endpoint:
            return client
        # dial OUTSIDE the lock: close() on the training thread's hot-
        # restage path takes _mu and must never wait behind this connect
        try:
            from edl_tpu.store.client import StoreClient

            client = StoreClient(endpoint, timeout=5.0)
        except Exception as exc:  # noqa: BLE001
            logger.debug("aot: no store client (%s)", exc)
            return None
        with self._mu:
            if self._client is None:
                self._client = client
                return client
            existing = self._client
        try:
            client.close()  # lost a (theoretical) publish race
        except Exception:  # noqa: BLE001
            pass
        return existing

    def _claim(self, world: int):
        """Returns a held Registration, True (no store — lone pod, rank 0
        compiles), or None (claimed/done elsewhere)."""
        client = self._store()
        if client is None:
            return True if self._env.global_rank == 0 else None
        from edl_tpu.discovery.registry import Registry
        from edl_tpu.utils.exceptions import EdlStoreError

        try:
            reg, _holder = Registry(
                client, self._env.job_id or "job"
            ).register_if_absent(
                AOT_SERVICE, str(world),
                ("%s.%d" % (self._env.pod_id, self._env.global_rank)).encode(),
                ttl=60.0,
            )
        except EdlStoreError:
            return None  # transient store trouble: drop the rung this pass
        return reg

    def _finish_claim(self, world: int, reg, ok: bool) -> None:
        if reg is True:
            return
        if ok:
            client = self._store()
            if client is not None:
                from edl_tpu.discovery.registry import Registry
                from edl_tpu.utils.exceptions import EdlStoreError

                try:
                    Registry(client, self._env.job_id or "job").set_permanent(
                        AOT_SERVICE, str(world),
                        b"done:" + self._env.pod_id.encode(),
                    )
                except EdlStoreError:
                    pass
            reg.stop(delete=False)
        else:
            reg.stop(delete=True)

    # -- the compile loop --------------------------------------------------

    def _run(self) -> None:
        # the whole thread body is contained: speculation is "a counted
        # outcome, never a crash" and that must hold for failures OUTSIDE
        # _compile_rung too — jax.devices() itself can raise mid-restage
        # (backend re-init race) and an unhandled thread death would both
        # skip the closure release and dump a traceback over training
        try:
            self._run_inner()
        except Exception as exc:  # noqa: BLE001
            _M_AOT.inc(outcome="failed")
            logger.warning("aot: ladder aborted (%s)", exc)
        finally:
            with self._mu:
                self._compile_for = None

    def _run_inner(self) -> None:
        try:
            # best-effort thread-level niceness (Linux: a tid is a valid
            # PRIO_PROCESS target) — the ladder must lose CPU arbitration
            # to the training step it runs beside
            os.setpriority(
                os.PRIO_PROCESS, threading.get_native_id(),
                int(os.environ.get("EDL_AOT_NICE", "10")),
            )
        except (AttributeError, OSError, ValueError):
            pass
        if self._stop.wait(timeout=self._delay):
            return
        import jax

        devices = jax.devices()
        local_ids = {d.id for d in jax.local_devices()}
        per_proc = devices_per_process(self._env)
        deferred: List[int] = []
        for world in self._worlds:
            if self._stop.is_set():
                return
            ndev = world * per_proc
            if ndev > len(devices):
                # grow rung: the mesh needs devices this process cannot
                # see — warm.py shadow stages and the cache exchange own
                # this side of the ladder
                _M_AOT.inc(outcome="skipped_grow")
                obs_events.record(
                    "aot", component="aot", world=world,
                    outcome="skipped_grow",
                )
                continue
            if not any(d.id in local_ids for d in devices[:ndev]):
                # the target sub-mesh excludes every local device: the
                # executable could not even load here (and a surviving
                # peer whose device IS in it holds the claimable work)
                _M_AOT.inc(outcome="skipped_nonlocal")
                continue
            reg = self._claim(world)
            if reg is None:
                _M_AOT.inc(outcome="skipped_claimed")
                deferred.append(world)
                continue
            self._compile_rung(world, reg)
        # second chance for rungs a peer had claimed: a FAILED peer
        # compile deletes its lease and writes no done marker, so one
        # bounded re-pass picks the rung up instead of stranding it
        # until the next stage re-arms a ladder
        for world in deferred:
            if self._stop.wait(timeout=self._RETRY_DELAY):
                return
            reg = self._claim(world)
            if reg is None:
                continue  # done, still being compiled, or store trouble
            self._compile_rung(world, reg)
        # _run's finally then drops the (state, batch) closure: on TPU it
        # pins the first prefetched batch (and, for non-donating steps, a
        # full state duplicate) in HBM if held past the last rung

    _RETRY_DELAY = 5.0  # deferred-rung recheck (one peer-compile's width)

    def _compile_rung(self, world: int, reg) -> None:
        compile_for = self._compile_for  # close() may null it under us
        if compile_for is None:
            self._finish_claim(world, reg, False)
            return
        ok = False
        indivisible = False
        t0 = time.monotonic()
        try:
            with self._ledger.phase("aot_compile", cause="w%d" % world):
                if _FP_COMPILE.armed:
                    _FP_COMPILE.fire(world=world)
                _in_ladder.active = True
                try:
                    compile_for(world)
                finally:
                    _in_ladder.active = False
            ok = True
        except RungUnavailable as exc:
            # a permanent property of the model/window (e.g. an fsdp dim
            # not divisible over the neighbor mesh), not a breakage —
            # must not pollute the failed counter or warn every stage
            indivisible = True
            logger.debug("aot: world=%d rung unavailable (%s)", world, exc)
        except Exception as exc:  # noqa: BLE001 — speculation never kills training
            logger.warning(
                "aot: speculative compile for world=%d failed (%s)",
                world, exc,
            )
        finally:
            self._finish_claim(world, reg, ok)
        _M_AOT.inc(
            outcome="ok" if ok
            else ("skipped_indivisible" if indivisible else "failed")
        )
        obs_events.record(
            "aot", fsync=True, component="aot", world=world,
            outcome="ok" if ok
            else ("skipped_indivisible" if indivisible else "failed"),
            dur=round(time.monotonic() - t0, 3),
        )
        if ok:
            self.compiled.append(world)
            logger.info(
                "aot: world=%d step compiled ahead of time (%.1fs)",
                world, time.monotonic() - t0,
            )


def _scale_dim(shape, spec, mesh, new_mesh, scale_axes) -> Tuple:
    """Scale every dim of ``shape`` sharded over an axis in
    ``scale_axes`` by that axis's size ratio (the dp-batch contract:
    per-worker rows constant, global rows ∝ world)."""
    dims = list(shape)
    for i, part in enumerate(spec or ()):
        names = part if isinstance(part, tuple) else (part,)
        for name in names:
            if name in scale_axes:
                old = mesh.shape[name]
                new = new_mesh.shape[name]
                if old and dims[i] % old == 0:
                    dims[i] = dims[i] // old * new
    return tuple(dims)


def make_neighbor_compiler(
    step,
    state,
    batch,
    mesh_axes: Optional[Dict[str, int]] = None,
    batch_axis: str = "dp",
    devices_per_proc: Optional[int] = None,
    on_compiled: Optional[Callable[[int, object], None]] = None,
):
    """Build the ``compile_for(world)`` callback for :class:`AotLadder`
    from a live steady-state (step, state, batch) triple.

    The avals are mirrored from the live arrays — shapes, dtypes and
    sharding SPECS — and re-bound to a mesh of the target world's device
    prefix: state leaves keep their global shapes (fsdp shards them over
    more or fewer devices; divisibility failures skip the rung), batch
    dims sharded over ``batch_axis`` scale with the world size
    (per-worker rows are the constant). Lowering with ShapeDtypeStructs
    is a jax trace + XLA compile — no data, no execution — and the
    compile lands in the persistent cache under the portable key the
    future stage will look up.
    """
    import jax
    from jax.sharding import NamedSharding

    from edl_tpu.parallel import make_mesh

    live_mesh = None
    for leaf in jax.tree.leaves((state, batch)):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and getattr(sharding, "mesh", None) is not None:
            live_mesh = sharding.mesh
            break
    if live_mesh is None:
        raise ValueError("no NamedSharding-placed leaf to mirror avals from")
    axes = dict(mesh_axes) if mesh_axes else {batch_axis: -1}

    def as_sds(leaf, new_mesh, scale_axes):
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        shape = _scale_dim(
            leaf.shape, spec, live_mesh, new_mesh, scale_axes
        )
        new_sharding = (
            NamedSharding(new_mesh, spec) if spec is not None else None
        )
        for i, part in enumerate(spec or ()):
            names = part if isinstance(part, tuple) else (part,)
            for name in names:
                if name and shape[i] % new_mesh.shape[name]:
                    raise RungUnavailable(
                        "dim %d (%d) not divisible over %r=%d"
                        % (i, shape[i], name, new_mesh.shape[name])
                    )
        return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=new_sharding)

    # world counts PROCESSES; the target mesh needs the device prefix of
    # world x devices-per-process (on real TPU a process owns several
    # chips — a 1-device-per-world mesh would speculate shapes no real
    # stage ever runs)
    per_proc = (
        devices_per_proc
        if devices_per_proc
        else devices_per_process(None)
    )

    def compile_for(world: int) -> None:
        devices = jax.devices()[: world * per_proc]
        new_mesh = make_mesh(axes, devices=devices)
        state_sds = jax.tree.map(
            lambda x: as_sds(x, new_mesh, ()), state
        )
        batch_sds = jax.tree.map(
            lambda x: as_sds(x, new_mesh, (batch_axis,)), batch
        )
        with new_mesh:
            compiled = step.lower(state_sds, batch_sds).compile()
        if on_compiled is not None:
            # the rung's compiled executable in hand: the memory plane
            # harvests its memory_analysis() here (the plan is free —
            # the compile already happened for the resize ladder)
            try:
                on_compiled(world, compiled)
            except Exception as exc:  # noqa: BLE001 — telemetry never fails a rung
                logger.debug(
                    "aot: on_compiled hook failed for world=%d: %s",
                    world, exc,
                )

    return compile_for


# -- the cache exchange -------------------------------------------------------

_TMP_MARK = ".edlpull"


def _is_entry(name: str) -> bool:
    """True for a shippable persistent-cache entry file name. XLA's
    ``-atime`` sidecars (rewritten on every hit — literally access-time
    records), in-flight pull temps and dotfiles are excluded. The single
    definition of "what is a cache entry" — the manifest scanners must
    agree or published manifests drift from what peers can serve."""
    return not (
        name.endswith("-atime") or _TMP_MARK in name or name.startswith(".")
    )


def _safe_name(name: str) -> bool:
    """True when a PEER-supplied entry name is a bare filename. Enforced
    on both exchange directions: the server never reads a path-shaped
    name out of its cache dir, and the puller never writes one — a
    hostile manifest naming ``../../...`` must not choose where entry
    bytes land."""
    return bool(name) and "/" not in name and "\\" not in name and not name.startswith(".")


def _digest_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def _scan_dir(
    cache_dir: str, digests: Dict[str, Tuple[float, int, str]]
) -> Tuple[Dict[str, Tuple[float, int, str]], Dict[str, Dict]]:
    """THE definition of "what is a publishable cache entry": one
    enumeration shared by every manifest scanner, or published manifests
    drift from what peers can serve. ``digests`` memoizes by
    (mtime, size) so an unchanged file is a stat, not a re-digest; pass
    ``{}`` for a full scan. Returns ``(fresh_digests, manifest)`` where
    manifest is ``{entry_name: {"sha": hex, "size": n}}`` — entry names
    double as cache keys, so a manifest diff IS a key diff."""
    fresh: Dict[str, Tuple[float, int, str]] = {}
    out: Dict[str, Dict] = {}
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return fresh, out
    for name in names:
        if not _is_entry(name):
            continue
        path = os.path.join(cache_dir, name)
        try:
            st = os.stat(path)
            cached = digests.get(name)
            if cached and cached[0] == st.st_mtime and cached[1] == st.st_size:
                sha = cached[2]
            else:
                sha = _digest_file(path)
            fresh[name] = (st.st_mtime, st.st_size, sha)
            out[name] = {"sha": sha, "size": st.st_size}
        except OSError:
            continue
    return fresh, out


def scan_manifest(cache_dir: str) -> Dict[str, Dict]:
    """One-shot full scan (see :func:`_scan_dir`)."""
    return _scan_dir(cache_dir, {})[1]


class CacheExchange:
    """Pod-side half of the exchange: manifest publication + entry server.

    Owned by the LAUNCHER (pod-scoped, survives worker restarts across
    stages); sharing the launcher's store client. ``refresh()`` is cheap
    and throttled internally — call it from the supervision loop; it
    rescans the cache dir (digesting only new/changed files) and
    republishes the manifest when it changed.
    """

    _REFRESH_EVERY = 5.0

    def __init__(
        self, cache_dir: str, client, job_id: str, pod_id: str,
        host: str = "0.0.0.0", port: int = 0,
    ) -> None:
        self.cache_dir = cache_dir
        self._client = client
        self.job_id = job_id
        self.pod_id = pod_id
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._host = host
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._refresh_thread: Optional[threading.Thread] = None
        self._refresh_lock = threading.Lock()
        self._digests: Dict[str, Tuple[float, int, str]] = {}  # name -> (mtime, size, sha)
        self._published: Optional[str] = None
        self._last_refresh = 0.0

    @property
    def endpoint(self) -> str:
        from edl_tpu.utils.net import get_host_ip

        host = self._host if self._host not in ("", "0.0.0.0") else get_host_ip()
        return "%s:%d" % (host, self.port)

    def start(self) -> "CacheExchange":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="edl-cache-exchange", daemon=True
        )
        self._accept_thread.start()
        # ALL digest work — the initial scan included — lives on the
        # exchange's own thread, never the caller's: the common restage
        # case relaunches a launcher over a WARM cache dir (GBs of
        # TPU-sized entries), and sha256 over that inline in start()
        # or on the supervision loop would stall worker spawn / drain
        # windows for seconds. The manifest appears moments after
        # start() returns; peers that race it simply pull on their next
        # look.
        self._refresh_thread = threading.Thread(
            target=self._refresh_loop, name="edl-cache-exchange-scan",
            daemon=True,
        )
        self._refresh_thread.start()
        return self

    def _refresh_loop(self) -> None:
        self.refresh(force=True)  # initial publish, off the start() path
        while not self._stop.wait(timeout=self._REFRESH_EVERY):
            self.refresh(force=True)

    def stop(self) -> None:
        self._stop.set()
        # the scan thread must be gone before the retraction below, or
        # an in-flight refresh republishes the manifest right after we
        # delete it
        if self._refresh_thread is not None:
            self._refresh_thread.join(timeout=2.0)
        # retract the manifest: it is a plain (unleased) key, so without
        # this a departed pod's entry outlives it and every later pull
        # burns budget dialing a dead endpoint (a SIGKILLed pod still
        # leaves one behind — the per-peer dial cap in pull_missing is
        # the backstop for that case)
        if self._published is not None:
            try:
                self._client.delete(
                    "/%s/%s/%s" % (self.job_id, MANIFEST_SERVICE, self.pod_id)
                )
            except Exception:  # noqa: BLE001 — best-effort retraction
                pass
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    # -- manifest ----------------------------------------------------------

    def _scan_incremental(self) -> Dict[str, Dict]:
        """:func:`_scan_dir` against the memoized digest map — the
        steady-state refresh cost is one listdir + a stat per entry."""
        self._digests, out = _scan_dir(self.cache_dir, self._digests)
        return out

    def refresh(self, force: bool = False) -> None:
        """Republish the manifest if the cache dir changed. Runs on the
        exchange's own scan thread in steady state (manual calls are
        fine — serialized by a lock). Best-effort: a sick store delays
        the next pod's pull, it never breaks this one."""
        with self._refresh_lock:
            self._refresh_locked(force)

    # edl: blocking-ok(hashing under _refresh_lock is the design: the lock exists only to serialize the exchange's own scan thread against manual refresh() calls — nothing latency-critical contends it, PR-8 moved all scans off the supervision loop)
    def _refresh_locked(self, force: bool) -> None:
        now = time.monotonic()
        if not force and now - self._last_refresh < self._REFRESH_EVERY:
            return
        self._last_refresh = now
        entries = self._scan_incremental()
        # the change check must exclude the publication timestamp: with
        # ts inside, every throttle window republishes an identical
        # manifest — steady store-journal chatter that rides the
        # replication stream of an HA control plane for nothing
        payload = {
            "endpoint": self.endpoint,
            "entries": {n: e["sha"] for n, e in sorted(entries.items())},
        }
        body = json.dumps(payload, sort_keys=True)
        if body == self._published:
            return
        payload["ts"] = time.time()
        try:
            self._client.put(
                "/%s/%s/%s" % (self.job_id, MANIFEST_SERVICE, self.pod_id),
                json.dumps(payload, sort_keys=True).encode(),
            )
            self._published = body
        except Exception as exc:  # noqa: BLE001
            logger.debug("cache-exchange manifest publish failed: %s", exc)

    # -- serving -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(sock,), daemon=True
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        from edl_tpu.rpc.wire import pack_frame, read_frame_blocking

        try:
            with sock:
                sock.settimeout(30.0)
                req = read_frame_blocking(sock)
                from edl_tpu.rpc.wire import TC_FIELD, server_span

                if req.get("m") != "cache_pull":
                    sock.sendall(pack_frame(
                        {"i": req.get("i", 0), "ok": False,
                         "err": {"etype": "EdlStoreError",
                                 "detail": "unknown method"}}
                    ))
                    return
                from edl_tpu.rpc.wire import read_entries_capped

                cap = int(os.environ.get(
                    "EDL_CACHE_PULL_MAX_BYTES", str(64 << 20)
                ))
                # per-method server latency + caller-linked span when the
                # pulling pod propagated its restage trace context
                with server_span(
                    "cache_pull", req.get(TC_FIELD), server="cache"
                ):
                    # the manifest is the only namespace a peer may name:
                    # never serve a path-shaped name out of the cache dir
                    entries, truncated, sent = read_entries_capped(
                        req.get("names", ()),
                        lambda name: (
                            os.path.join(self.cache_dir, name)
                            if _safe_name(name) else None
                        ),
                        cap,
                    )
                sock.sendall(pack_frame(
                    {"i": req.get("i", 0), "ok": True, "entries": entries,
                     "truncated": truncated}
                ))
                _M_XCHG_BYTES.inc(sent, dir="tx")
        except Exception as exc:  # noqa: BLE001 — a sick peer is its problem
            logger.debug("cache-exchange serve failed: %s", exc)


def read_manifests(client, job_id: str) -> Dict[str, Dict]:
    """``{pod_id: manifest}`` for every published pod manifest."""
    out: Dict[str, Dict] = {}
    prefix = "/%s/%s/" % (job_id, MANIFEST_SERVICE)
    try:
        rows, _rev = client.range(prefix)
    except Exception as exc:  # noqa: BLE001
        logger.debug("cache-exchange manifest read failed: %s", exc)
        return out
    for key, value, _c, _m in rows:
        try:
            out[key[len(prefix):]] = json.loads(value)
        except ValueError:
            continue
    return out


def pull_missing(
    cache_dir: str,
    client=None,
    endpoint: str = "",
    job_id: str = "",
    own_pod: str = "",
    deadline: Optional[float] = None,
    chunk: int = 16,
) -> Dict[str, int]:
    """Diff peer manifests against ``cache_dir`` and pull what is missing.

    Returns ``{"pulled": n, "bytes": n, "skipped_bad": n, "peers": n}``.
    Bounded (``deadline`` seconds, default ``EDL_CACHE_PULL_BUDGET`` =
    10) and exception-contained: ANY failure — peer gone, frame torn,
    digest mismatch (the ``store.cache.exchange`` corrupt drill) — skips
    that entry or peer and the resize degrades to a normal compile.
    Entries land via write-to-temp + atomic rename, digest-verified
    first, so a torn pull can never poison the cache.
    """
    stats = {"pulled": 0, "bytes": 0, "skipped_bad": 0, "peers": 0}
    if not cache_dir:
        return stats
    if deadline is None:
        deadline = float(os.environ.get("EDL_CACHE_PULL_BUDGET", "10"))
    t_end = time.monotonic() + deadline
    owns_client = False
    if client is None:
        if not endpoint:
            return stats
        try:
            from edl_tpu.store.client import StoreClient

            client = StoreClient(endpoint, timeout=min(5.0, deadline))
            owns_client = True
        except Exception as exc:  # noqa: BLE001
            logger.debug("cache pull: no store (%s)", exc)
            return stats
    # restage-trace segment: the pull is one hop of the restage critical
    # path (spawn -> CACHE PULL -> restore -> first jit), and the span's
    # context rides each cache_pull RPC to the serving peer
    import contextlib as _contextlib

    span = (
        obs_trace.child_span("cache_pull")
        if obs_trace.PROPAGATION.armed
        else _contextlib.nullcontext()
    )
    try:
        manifests = read_manifests(client, job_id)
        try:
            local = set(os.listdir(cache_dir))
        except OSError:
            os.makedirs(cache_dir, mode=0o700, exist_ok=True)
            local = set()
        t0 = time.monotonic()
        with span:
            for pod, manifest in manifests.items():
                if pod == own_pod or time.monotonic() > t_end:
                    continue
                peer = manifest.get("endpoint", "")
                wanted = {
                    name: sha
                    for name, sha in (manifest.get("entries") or {}).items()
                    # the write direction enforces the same bare-filename rule
                    # the server does: a hostile manifest must not pick where
                    # pulled bytes land
                    if name not in local and _safe_name(name)
                }
                if not peer or not wanted:
                    continue
                stats["peers"] += 1
                names = sorted(wanted)
                while names and time.monotonic() <= t_end:
                    batch, names = names[:chunk], names[chunk:]
                    got, truncated = _pull_chunk(
                        peer, batch,
                        # per-dial cap: a dead endpoint (SIGKILLed pod whose
                        # manifest survived) must cost one bounded connect,
                        # not the whole remaining pull budget
                        max(0.5, min(
                            float(os.environ.get(
                                "EDL_CACHE_PULL_PEER_TIMEOUT", "5"
                            )),
                            t_end - time.monotonic(),
                        )),
                    )
                    if not got:
                        break  # peer sick/gone: stop dialing it, try the next
                    # entries the server pushed out of a byte-capped response
                    # come back later; got nonempty guarantees progress
                    names.extend(truncated)
                    for name, data in got.items():
                        if _FP_EXCHANGE.armed:
                            try:
                                data = _FP_EXCHANGE.fire(data, name=name[:32])
                            except ConnectionError:
                                stats["skipped_bad"] += 1
                                continue
                        sha = hashlib.sha256(data).hexdigest()
                        if sha != wanted.get(name):
                            # corrupted in flight or torn at the peer: skip —
                            # the next stage simply compiles this one itself
                            stats["skipped_bad"] += 1
                            logger.warning(
                                "cache pull: digest mismatch for %s from %s; "
                                "entry dropped (degrades to a compile)",
                                name[:48], pod[:8],
                            )
                            continue
                        tmp = os.path.join(
                            cache_dir,
                            "%s%s.%d" % (name, _TMP_MARK, os.getpid()),
                        )
                        try:
                            with open(tmp, "wb") as fh:
                                fh.write(data)
                                # a digest-verified entry must not be torn by
                                # the next SIGKILL: rename persists the name,
                                # fsync persists the bytes
                                fh.flush()
                                os.fsync(fh.fileno())
                            os.replace(tmp, os.path.join(cache_dir, name))
                        except OSError as exc:
                            logger.warning("cache pull: write failed: %s", exc)
                            try:
                                os.unlink(tmp)
                            except OSError:
                                pass
                            continue
                        local.add(name)
                        stats["pulled"] += 1
                        stats["bytes"] += len(data)
                        _M_XCHG_BYTES.inc(len(data), dir="rx")
        if stats["pulled"] or stats["skipped_bad"]:
            obs_events.record(
                "exchange", fsync=True, component="aot",
                pulled=stats["pulled"], bytes=stats["bytes"],
                skipped_bad=stats["skipped_bad"],
                dur=round(time.monotonic() - t0, 3),
            )
            logger.info(
                "cache exchange: pulled %d entr%s (%d bytes) from %d "
                "peer(s)%s",
                stats["pulled"], "y" if stats["pulled"] == 1 else "ies",
                stats["bytes"], stats["peers"],
                ", %d bad skipped" % stats["skipped_bad"]
                if stats["skipped_bad"] else "",
            )
    except Exception as exc:  # noqa: BLE001 — the pull is a perf lever, never a gate
        logger.warning("cache pull failed (%s); continuing uncached", exc)
    finally:
        if owns_client:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
    return stats


def _pull_chunk(
    peer: str, names: List[str], timeout: float
) -> Tuple[Dict[str, bytes], List[str]]:
    """One bounded cache_pull RPC. Returns ``(entries, truncated)`` —
    ``truncated`` names the server pushed out of a byte-capped response
    for the caller to re-request; both empty on any transport failure."""
    from edl_tpu.rpc.wire import request_once

    try:
        resp = request_once(
            peer, {"i": 1, "m": "cache_pull", "names": names},
            timeout=min(timeout, 30.0),
        )
    except Exception as exc:  # noqa: BLE001
        logger.debug("cache pull from %s failed: %s", peer, exc)
        return {}, []
    if not resp.get("ok"):
        return {}, []
    entries = resp.get("entries") or {}
    return {
        str(name): bytes(data)
        for name, data in entries.items()
        if isinstance(data, (bytes, bytearray))
    }, [str(n) for n in (resp.get("truncated") or ())]
