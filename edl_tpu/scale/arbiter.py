"""Multi-job arbitration of one shared device pool.

Gavel's framing (Narayanan et al., OSDI '20): the cluster objective is
the weighted sum of per-job goodputs, and the allocator's job is the
argmax over feasible allocations. The feasible set here is integral pod
counts with two hard structural rules:

- **gang floor** — a job gets >= its min world or exactly 0; an
  allocation strictly between strands a gang-scheduled job (its
  collective can't form) while still burning pods;
- **priority admission** — when the pool can't fit every job's floor,
  lower-priority jobs are preempted to 0 first (the drain plane turns
  that into graceful `preempt/{pod}` notices, not kills).

Above the floors, remaining pods are water-filled one at a time to the
job whose weighted *marginal* modeled goodput is highest — a greedy
argmax that is exact here because :func:`~edl_tpu.scale.decide
.model_goodput` is concave in ``n`` (throughput gains shrink with
alpha, efficiency strictly decays), so marginal gains are monotone.

Gang *sequencing* lives here too (:func:`release_targets`): a grow must
not be released until the shrinks that fund it have actually happened,
or the pool transiently oversubscribes and both restages collide.
Pure functions, stdlib only — tests/test_scale.py drives the tables.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional

from edl_tpu.scale import decide as scale_decide

__all__ = ["JobDemand", "allocate", "release_targets"]


@dataclasses.dataclass(frozen=True)
class JobDemand:
    """One job's standing in the arbitration round."""

    job_id: str
    min_world: int = 1
    max_world: int = 1024
    priority: int = 0            # higher wins admission
    weight: float = 1.0          # cluster-objective weight
    stats: Optional[scale_decide.JobStats] = None
    params: scale_decide.ScaleParams = dataclasses.field(
        default_factory=scale_decide.ScaleParams
    )
    active: bool = True          # wants to run (has or is owed pods)


def _gain(d: JobDemand, n: int) -> float:
    """Weighted marginal goodput of this job's (n)th pod."""
    g1 = scale_decide.model_goodput(n, d.params, d.stats)
    g0 = scale_decide.model_goodput(n - 1, d.params, d.stats)
    return d.weight * (g1 - g0)


def allocate(demands: Iterable[JobDemand], capacity: int) -> Dict[str, int]:
    """The cluster-goodput-maximizing allocation of ``capacity`` pods.

    Returns ``{job_id: pods}`` for every demand (inactive jobs and
    jobs that lost admission get 0). Deterministic: admission order is
    (priority desc, job_id asc); water-filling tie-breaks the same way.
    """
    jobs = [d for d in demands if d.active]
    out: Dict[str, int] = {d.job_id: 0 for d in demands}
    if capacity <= 0 or not jobs:
        return out
    order = sorted(jobs, key=lambda d: (-d.priority, d.job_id))
    admitted: List[JobDemand] = []
    free = capacity
    for d in order:
        floor = max(1, d.min_world)
        if floor <= free:
            admitted.append(d)
            out[d.job_id] = floor
            free -= floor
    # water-fill: one pod at a time to the best weighted marginal gain
    while free > 0:
        best: Optional[JobDemand] = None
        best_gain = 0.0
        for d in admitted:
            n = out[d.job_id]
            if n >= d.max_world:
                continue
            g = _gain(d, n + 1)
            if best is None or g > best_gain + 1e-12:
                best, best_gain = d, g
        if best is None or best_gain <= 0:
            break
        out[best.job_id] += 1
        free -= 1
    return out


def release_targets(
    targets: Dict[str, int], actuals: Dict[str, int]
) -> Dict[str, int]:
    """The subset of ``targets`` safe to publish *now* (gang
    sequencing).

    Shrinks and preempts release immediately — they free pods and can
    never oversubscribe. Grows release only once NO shrink is still in
    flight (every job's actual world is at or below its target), i.e.
    the pods the grows spend have genuinely been returned to the pool.
    The scaler re-sweeps until that holds, so a deferred grow releases
    on the first sweep after the funding shrinks settle.
    """
    shrinking = [
        j for j, t in targets.items() if t < actuals.get(j, 0)
    ]
    out: Dict[str, int] = {}
    for job, t in targets.items():
        cur = actuals.get(job, 0)
        if t <= cur or not shrinking:
            out[job] = t
    return out
