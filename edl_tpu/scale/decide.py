"""Per-job scaling decisions as a pure function of observed stats.

The objective is Pollux's (Qiao et al., OSDI '21) goodput:

    goodput(n) = throughput(n) * efficiency(batch(n))

with a simple two-parameter system model on each factor:

- ``throughput(n) = n * rate1 / (1 + alpha * (n - 1))`` — linear scaling
  bent by a contention coefficient ``alpha`` (0 = perfect scaling). The
  per-pod rate ``rate1`` cancels out of every comparison the engine
  makes, so an uncalibrated job still ranks world sizes correctly.
- ``efficiency(B) = (phi + b0) / (phi + B)`` with ``B = n * b0`` — the
  statistical-efficiency discount from running a bigger global batch,
  saturating at the gradient-noise-scale ``phi`` (PR 15's estimator
  feeds the live value; a large ``phi`` means big batches are still
  efficient, a small one means extra pods buy mostly wasted epochs).

Decisions carry *hysteresis* (a move must beat the current world by a
relative margin, or the controller oscillates on noise) and *cooldown*
(a restage just happened; let the new world show its rate before
re-deciding). Both are knobs: ``EDL_SCALE_HYSTERESIS``,
``EDL_SCALE_COOLDOWN`` (plus ``EDL_SCALE_ALPHA`` / ``EDL_SCALE_GNS``
model priors). Everything here is deterministic and store-free — the
decision-table tests in tests/test_scale.py drive it directly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "ScaleParams",
    "JobStats",
    "Decision",
    "model_goodput",
    "best_world",
    "decide_world",
    "fit_alpha",
    "params_from_env",
]

# decision kinds — the full grammar the scale plane speaks
GROW = "grow"
SHRINK = "shrink"
HOLD = "hold"
PREEMPT = "preempt"  # taken to zero: gang floor says min-or-nothing

_DEF_ALPHA = 0.05
_DEF_GNS = 32.0
_DEF_HYSTERESIS = 0.15
_DEF_COOLDOWN = 30.0

# a measured goodput ratio damps the curve but never flattens it: a
# freshly-restaged job legitimately reports ~0 (its wall time so far IS
# all restage), and a flat-zero curve would zero every marginal gain,
# collapse the arbiter's water-fill to the gang floor, and trip the
# mandatory (cooldown-bypassing) shrink — grow -> shrink thrash on
# every restage. Caught live by the PR-17 verify drill.
_HEALTH_FLOOR = 0.05


@dataclasses.dataclass(frozen=True)
class ScaleParams:
    """Model priors + controller damping for one job."""

    alpha: float = _DEF_ALPHA        # scaling contention (0 = perfect)
    gns: float = _DEF_GNS            # gradient-noise-scale prior (phi)
    batch_per_pod: float = 1.0       # b0: global batch grows n * b0
    hysteresis: float = _DEF_HYSTERESIS  # relative gain a move must clear
    cooldown_s: float = _DEF_COOLDOWN    # quiet time after an acted decision


def params_from_env(base: Optional[ScaleParams] = None) -> ScaleParams:
    """Layer the ``EDL_SCALE*`` knobs over ``base``: a set (non-empty)
    knob wins, an unset one falls through to the base value — so a
    caller-supplied prior survives when the env is silent. Single read
    site per knob (the env-registry lint tracks these); the literal
    defaults live on :class:`ScaleParams` itself."""
    b = base if base is not None else ScaleParams()
    return ScaleParams(
        alpha=float(os.environ.get("EDL_SCALE_ALPHA") or b.alpha),
        gns=float(os.environ.get("EDL_SCALE_GNS") or b.gns),
        batch_per_pod=b.batch_per_pod,
        hysteresis=float(
            os.environ.get("EDL_SCALE_HYSTERESIS") or b.hysteresis
        ),
        cooldown_s=float(
            os.environ.get("EDL_SCALE_COOLDOWN") or b.cooldown_s
        ),
    )


@dataclasses.dataclass(frozen=True)
class JobStats:
    """One job's observed signals, as the scaler scraped them."""

    world: int                      # actual pods right now
    per_pod_rate: float = 1.0       # examples/s/pod (cancels in ranking)
    goodput_ratio: float = 1.0      # ledger train/wall; damps the model
    gns: Optional[float] = None     # measured noise scale; None = prior
    stragglers: int = 0             # alert pressure; reads as contention


@dataclasses.dataclass(frozen=True)
class Decision:
    """One decision record — what scale/target serializes."""

    kind: str                       # grow | shrink | hold | preempt
    target: int                     # pods (0 only with kind=preempt)
    cause: str
    score: float                    # model goodput at target
    seq: int = 0                    # global decision sequence number
    job_id: str = ""
    ts: float = 0.0                 # decision wall-time (cooldown anchor)


def model_goodput(
    n: int,
    params: ScaleParams,
    stats: Optional[JobStats] = None,
) -> float:
    """The modeled goodput of running at ``n`` pods (examples/s scaled
    by statistical efficiency); 0 for n <= 0.

    Two observed health signals damp the curve:

    - ``stats.stragglers`` reads as *measured* contention — each firing
      pressure rule adds one alpha-prior of slope, so extra pods look
      worse and the per-job argmax shifts down;
    - ``stats.goodput_ratio`` (the ledger's train/wall fraction) scales
      the whole curve, floored at ``_HEALTH_FLOOR`` so a transient zero
      (a job mid-restage has spent ALL its wall time restaging) damps
      rather than erases it. It cancels inside this job's own argmax
      and hysteresis comparisons, but it damps the weighted *marginal*
      gains the arbiter water-fills by — an unhealthy job funds a
      healthy one.
    """
    if n <= 0:
        return 0.0
    rate1 = stats.per_pod_rate if stats is not None else 1.0
    if rate1 <= 0:
        rate1 = 1.0
    phi = params.gns
    alpha = params.alpha
    health = 1.0
    if stats is not None:
        if stats.gns is not None and stats.gns > 0:
            phi = stats.gns
        if stats.stragglers > 0:
            alpha += _DEF_ALPHA * stats.stragglers
        health = min(max(stats.goodput_ratio, _HEALTH_FLOOR), 1.0)
    b0 = max(params.batch_per_pod, 1e-9)
    throughput = n * rate1 / (1.0 + alpha * (n - 1))
    efficiency = (phi + b0) / (phi + n * b0)
    return throughput * efficiency * health


def best_world(
    lo: int,
    hi: int,
    params: ScaleParams,
    stats: Optional[JobStats] = None,
) -> int:
    """argmax of :func:`model_goodput` over ``[lo, hi]`` (smallest world
    wins ties — fewer pods for the same goodput is strictly better for
    the cluster)."""
    lo = max(1, lo)
    if hi < lo:
        return lo
    best_n, best_g = lo, model_goodput(lo, params, stats)
    for n in range(lo + 1, hi + 1):
        g = model_goodput(n, params, stats)
        if g > best_g * (1.0 + 1e-9):
            best_n, best_g = n, g
    return best_n


def decide_world(
    stats: JobStats,
    capacity: int,
    min_world: int,
    max_world: int,
    params: ScaleParams,
    last: Optional[Decision] = None,
    now: float = 0.0,
    mem_cap: Optional[int] = None,
) -> Decision:
    """One job's decision against ``capacity`` free-for-it pods.

    The grammar:

    - capacity below ``min_world`` -> ``preempt`` to 0 (gang floor: a
      job runs at >= min_world or not at all, never in between);
    - the current world EXCEEDS capacity -> ``shrink`` to the model
      argmax within capacity, unconditionally — the allocation is
      binding (another job was admitted onto those pods), so neither
      hysteresis nor cooldown may hold the preemption hostage;
    - the model argmax over ``[min_world, min(max_world, capacity)]``
      beats the current world by the hysteresis margin -> ``grow`` /
      ``shrink`` to it;
    - otherwise -> ``hold`` (including during cooldown after an acted
      decision — a restage must settle before the next one).

    ``mem_cap`` is the memory-plane fit verdict (obs/memory.fit_cap):
    the largest world whose published compile-time memory plan fits the
    device limit minus the safety margin, or None when no plan has been
    published (unknown never gates). The gate clamps *growth* — a
    target above the cap is walked down and the decision's cause says
    ``mem_unfit`` — but it never force-shrinks the current world: a
    running world is live evidence it fits, and the plan's margin is
    deliberately conservative.
    """
    if capacity < min_world:
        return Decision(
            PREEMPT, 0, "capacity %d < min world %d" % (capacity, min_world),
            0.0, ts=now,
        )
    hi_raw = min(max_world, capacity)
    lo = min_world
    cur = stats.world if stats.world > 0 else 0
    hi = hi_raw
    if mem_cap is not None and mem_cap < hi_raw:
        hi = max(mem_cap, cur)
        if hi < lo:
            # even the gang floor is unfit: refuse admission outright
            return Decision(
                HOLD, 0,
                "mem_unfit: no world in [%d, %d] fits device memory "
                "(largest fitting plan: %d pods)" % (lo, hi_raw, mem_cap),
                0.0, ts=now,
            )
    want = best_world(lo, hi, params, stats)
    want_raw = want if hi == hi_raw else best_world(lo, hi_raw, params, stats)
    mem_gated = want != want_raw
    if cur == 0:
        # not running yet: admission at the model optimum, no hysteresis
        cause = (
            "mem_unfit: admit capped at %d pods (model optimum %d over "
            "device memory)" % (want, want_raw)
            if mem_gated else "admit at model optimum"
        )
        return Decision(
            GROW, want, cause,
            model_goodput(want, params, stats), ts=now,
        )
    if cur > hi:
        return Decision(
            SHRINK, want,
            "allocation %d below world %d" % (hi, cur),
            model_goodput(want, params, stats), ts=now,
        )
    if (
        last is not None
        and last.kind in (GROW, SHRINK, PREEMPT)
        and params.cooldown_s > 0
        and (now - last.ts) < params.cooldown_s
    ):
        return Decision(
            HOLD, cur, "cooldown (%.0fs left)"
            % (params.cooldown_s - (now - last.ts)),
            model_goodput(cur, params, stats), ts=now,
        )
    g_cur = model_goodput(cur, params, stats)
    g_want = model_goodput(want, params, stats)
    if want != cur and g_want > g_cur * (1.0 + params.hysteresis):
        kind = GROW if want > cur else SHRINK
        cause = (
            "mem_unfit: grow capped at %d pods (model optimum %d over "
            "device memory)" % (want, want_raw)
            if mem_gated and kind == GROW
            else "model goodput %.3f -> %.3f at %d pods" % (g_cur, g_want, want)
        )
        return Decision(kind, want, cause, g_want, ts=now)
    if mem_gated and want == cur and want_raw > cur:
        return Decision(
            HOLD, cur,
            "mem_unfit: grow to %d refused (plan over device memory, "
            "cap %d)" % (want_raw, hi),
            g_cur, ts=now,
        )
    return Decision(HOLD, cur, "within hysteresis", g_cur, ts=now)


def fit_alpha(
    samples: Iterable[Tuple[int, float]],
    default: float = _DEF_ALPHA,
) -> float:
    """Fit the contention coefficient from observed ``(world,
    per-pod-rate)`` samples: the model says ``rate(n) = rate1 / (1 +
    alpha (n-1))``, so each pair of distinct worlds yields an alpha
    estimate; the fit is their median (robust to one noisy restage
    window). Falls back to ``default`` with <2 distinct worlds."""
    by_world: Dict[int, List[float]] = {}
    for n, r in samples:
        if n >= 1 and r > 0:
            by_world.setdefault(int(n), []).append(float(r))
    worlds = sorted(by_world)
    if len(worlds) < 2:
        return default
    rates = {n: sum(v) / len(v) for n, v in by_world.items()}
    estimates: List[float] = []
    for i, n1 in enumerate(worlds):
        for n2 in worlds[i + 1:]:
            if n1 == n2 or rates[n2] <= 0:
                continue
            # rate(n1)/rate(n2) = (1 + a(n2-1)) / (1 + a(n1-1))
            ratio = rates[n1] / rates[n2]
            denom = (n2 - 1) - ratio * (n1 - 1)
            if abs(denom) < 1e-12:
                continue
            a = (ratio - 1.0) / denom
            if a >= 0:
                estimates.append(a)
    if not estimates:
        return default
    estimates.sort()
    mid = len(estimates) // 2
    if len(estimates) % 2:
        return estimates[mid]
    return (estimates[mid - 1] + estimates[mid]) / 2.0
