"""Scale plane: the controller that decides cluster shape.

Everything below this package turns *observations* into *actions*:

- :mod:`edl_tpu.scale.decide` — the pure per-job decision engine: a
  Pollux-style goodput model (speedup x statistical efficiency) and the
  grow/shrink/hold/preempt decision grammar with hysteresis + cooldown.
- :mod:`edl_tpu.scale.arbiter` — the pure multi-job arbiter: cluster-
  goodput-maximizing allocation of one shared device pool with priority
  admission, gang floors (never strand a job below its min world) and
  gang-sequenced grow/shrink release.
- :mod:`edl_tpu.scale.scaler` — the daemon loop (``tools/edl_scaled.py``)
  that scrapes the monitor plane, runs the two pure halves, and *acts*
  by publishing ``scale/target`` docs the leader launcher reconciles
  through the existing drain/restage machinery.

The split is deliberate: decide/arbiter import nothing but stdlib, so
``tests/test_scale.py`` exercises the whole decision table without a
live cluster; only the scaler touches stores, flight recorders, traces.
"""

from edl_tpu.scale.decide import (  # noqa: F401
    Decision,
    JobStats,
    ScaleParams,
    decide_world,
    fit_alpha,
    model_goodput,
    params_from_env,
)
from edl_tpu.scale.arbiter import (  # noqa: F401
    JobDemand,
    allocate,
    release_targets,
)
from edl_tpu.scale.scaler import JobSpec, Scaler  # noqa: F401
