"""The scaler daemon: observations in, ``scale/target`` docs out.

One :class:`Scaler` arbitrates every configured job on one shared pool.
Each sweep it

1. **senses** — per job: the actual world from ``cluster/current``,
   ``edl_goodput_ratio`` / ``edl_train_steps_total`` /
   ``edl_train_grad_noise_scale`` scraped off the monitor plane's
   discovered endpoints, straggler pressure from the alert records (and
   a ``stats_override`` hook so drills can inject deterministic
   signals);
2. **decides** — :func:`~edl_tpu.scale.arbiter.allocate` splits the
   pool, :func:`~edl_tpu.scale.decide.decide_world` applies hysteresis
   + cooldown per job;
3. **acts** — gang-sequenced by :func:`~edl_tpu.scale.arbiter
   .release_targets`, each released decision is stamped with a global
   ``seq``, traced under the deterministic ``op_trace_id("scale",
   seq)`` root, fsync'd to the flight log as ``scale_decision``, and
   written to the store as ``scale/target`` (+ a rich
   ``scale/decision`` doc for edl-top). The leader launcher does the
   rest through drain/restage — the scaler never touches a pod.

The decision->restage latency contract: the scaler's ``scale_decision``
record and root span carry the same trace id the launcher stamps on
its ``reconcile`` segment, so ``edl-trace --op scale`` stitches the
full decision->restage path with no clock games.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from edl_tpu.cluster.contract import (
    CLUSTER_SERVICE,
    PREEMPT_SERVICE,
    SCALE_SERVICE,
)
from edl_tpu.cluster.model import Cluster
from edl_tpu.discovery.registry import Registry
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import memory as obs_memory
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import monitor as obs_monitor
from edl_tpu.obs import trace as obs_trace
from edl_tpu.scale import arbiter as scale_arbiter
from edl_tpu.scale import decide as scale_decide

logger = logging.getLogger("edl.scale")

__all__ = ["JobSpec", "Scaler", "TARGET_KEY", "DECISION_KEY"]

# keys under the scale service (see cluster/contract.py keyspace docs)
TARGET_KEY = "target"
DECISION_KEY = "decision"

# alert rules that register as pressure on a job's allocation
_PRESSURE_RULES = ("straggler-ejections", "goodput-degraded", "mfu-degraded")


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One arbitrated job: identity + gang limits + standing."""

    job_id: str
    min_world: int = 1
    max_world: int = 8
    priority: int = 0
    weight: float = 1.0

    @staticmethod
    def parse(text: str) -> "JobSpec":
        """``job[:min[:max[:priority]]]`` — the --job CLI grammar."""
        parts = text.split(":")
        return JobSpec(
            job_id=parts[0],
            min_world=int(parts[1]) if len(parts) > 1 else 1,
            max_world=int(parts[2]) if len(parts) > 2 else 8,
            priority=int(parts[3]) if len(parts) > 3 else 0,
        )


def _series_total(series: Dict[str, Dict[str, float]], metric: str) -> Optional[float]:
    vals = series.get(metric)
    if not vals:
        return None
    return sum(vals.values())


class Scaler:
    """Sense -> decide -> act loop over one store (see module doc)."""

    def __init__(
        self,
        store,
        jobs: List[JobSpec],
        interval: float = 5.0,
        capacity: Optional[object] = None,  # int, or () -> int; None = sum of actuals
        params: Optional[scale_decide.ScaleParams] = None,
        flight_dir: Optional[str] = None,
        trace_dir: Optional[str] = None,
        stats_override: Optional[Callable[[str], Optional[Dict]]] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        scrape_timeout: float = 1.0,
        procs_per_pod: int = 1,
    ) -> None:
        if not jobs:
            raise ValueError("scaler needs at least one JobSpec")
        ids = [j.job_id for j in jobs]
        if len(ids) != len(set(ids)):
            raise ValueError("duplicate job ids: %s" % sorted(ids))
        self.jobs = list(jobs)
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        self._capacity = capacity
        self.params = params if params is not None else scale_decide.params_from_env()
        self._stats_override = stats_override
        self._owns_client = False
        if isinstance(store, str):
            from edl_tpu.store.client import connect_store

            self.client = connect_store(store, timeout=5.0)
            self._owns_client = True
        else:
            self.client = store
        self._registries = {
            j.job_id: Registry(self.client, j.job_id) for j in self.jobs
        }
        reg = registry if registry is not None else obs_metrics.default_registry()
        self._m_decisions = reg.counter(
            "edl_scale_decisions_total", "acted scale decisions, by kind"
        )
        self._m_target = reg.gauge(
            "edl_scale_target_world", "published target world, by job"
        )
        self._m_unfit = reg.counter(
            "edl_scale_mem_unfit_total",
            "scale decisions gated by the memory-plane fit check "
            "(target walked down or refused with cause mem_unfit)",
        )
        self.procs_per_pod = max(1, int(procs_per_pod))
        self._recorder: Optional[obs_events.FlightRecorder] = None
        if flight_dir:
            self._recorder = obs_events.FlightRecorder(
                flight_dir, component="scaler"
            )
        self._tracer: Optional[obs_trace.SpanTracer] = None
        self._trace_path: Optional[str] = None
        if trace_dir:
            self._tracer = obs_trace.SpanTracer("scaler")
            self._trace_path = os.path.join(
                trace_dir, "scaler-%d.trace.json" % os.getpid()
            )
        self._seq = 0
        self._last: Dict[str, scale_decide.Decision] = {}
        self._published: Dict[str, int] = {}
        self._steps_hist: Dict[str, tuple] = {}   # job -> (ts, total steps)
        self._pressure: Dict[str, int] = {}       # job -> alert pressure count
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- job set -----------------------------------------------------------

    def add_job(self, spec: JobSpec, queued: bool = True) -> None:
        """Submit a job to the arbitration set mid-flight.

        With ``queued`` (the default) a ``scale/target`` of 0 pods is
        published immediately, BEFORE the job's pods exist: whenever
        they arrive, their launchers hold them (want=0, nothing
        published) until the arbiter genuinely admits the gang with a
        grow decision — admission is the scheduler's call, not a race
        against pod arrival."""
        with self._lock:
            if any(j.job_id == spec.job_id for j in self.jobs):
                raise ValueError("job %s already arbitrated" % spec.job_id)
            self.jobs.append(spec)
            self._registries[spec.job_id] = Registry(self.client, spec.job_id)
        if queued:
            self._act(
                spec.job_id,
                scale_decide.Decision(
                    "queued", 0, "submitted; awaiting admission", 0.0
                ),
                scale_decide.JobStats(world=0),
                time.time(),
            )
        self._wake.set()

    # -- sensing -----------------------------------------------------------

    def _job_complete(self, job_id: str) -> bool:
        """The job's trainee declared completion — it no longer bids
        for pods (its ``cluster/current`` doc is a permanent record of
        the last world and must not be read as demand)."""
        try:
            value = self.client.get("/%s/job/status" % job_id)
        except Exception:  # noqa: BLE001 — store blip: still bidding
            return False
        return bool(value) and value.strip() == b"COMPLETE"

    def _actual_world(self, job_id: str) -> int:
        """Published pods that are still coming to work: the launcher
        treats preempt-noticed pods as already gone (they drain, and
        the next generation excludes them), so they don't count here
        either. On a pause/preempt-to-0 no launcher may survive to
        publish a fresh generation at all — the victim's last
        ``cluster/current`` doc is permanent, and without the discount
        it would read as a shrink that never settles, deferring the
        preempting gang's grow forever."""
        reg = self._registries[job_id]
        try:
            meta = reg.get_server(CLUSTER_SERVICE, "current")
        except Exception:  # noqa: BLE001 — store mid-blip reads as unknown
            return 0
        if meta is None:
            return 0
        try:
            pod_ids = Cluster.from_json(meta.value).pod_ids()
        except (ValueError, KeyError):
            return 0
        if not pod_ids:
            return 0
        try:
            noticed = {m.name for m in reg.get_service(PREEMPT_SERVICE)}
        except Exception:  # noqa: BLE001 — blip: count the full roster
            noticed = set()
        return sum(1 for pid in pod_ids if pid not in noticed)

    def _scrape_job(self, job_id: str, now: float) -> Dict[str, float]:
        """Merged metric totals across the job's live endpoints."""
        merged: Dict[str, float] = {}
        try:
            targets = obs_http.discover_endpoints(self.client, job_id)
        except Exception:  # noqa: BLE001
            return merged
        ratios: List[float] = []
        gns: List[float] = []
        steps = 0.0
        saw_steps = False
        for info in targets.values():
            endpoint = info.get("endpoint", "")
            try:
                series = obs_http.fetch_metrics(endpoint, timeout=self.scrape_timeout)
            except Exception:  # noqa: BLE001 — dead endpoints are data too
                continue
            v = _series_total(series, "edl_goodput_ratio")
            if v is not None:
                ratios.append(v)
            v = _series_total(series, "edl_train_grad_noise_scale")
            if v is not None:
                gns.append(v)
            v = _series_total(series, "edl_train_steps_total")
            if v is not None:
                steps += v
                saw_steps = True
        if ratios:
            merged["goodput_ratio"] = sum(ratios) / len(ratios)
        if gns:
            merged["gns"] = sum(gns) / len(gns)
        if saw_steps:
            prev = self._steps_hist.get(job_id)
            self._steps_hist[job_id] = (now, steps)
            if prev is not None and now > prev[0] and steps >= prev[1]:
                merged["step_rate"] = (steps - prev[1]) / (now - prev[0])
        return merged

    def _job_stats(self, spec: JobSpec, now: float) -> scale_decide.JobStats:
        world = self._actual_world(spec.job_id)
        scraped = self._scrape_job(spec.job_id, now)
        stragglers = 0
        try:
            alerts = obs_monitor.read_alerts(self.client, spec.job_id)
        except Exception:  # noqa: BLE001
            alerts = {}
        for rule, doc in alerts.items():
            if rule in _PRESSURE_RULES and doc.get("state") == "firing":
                stragglers += 1
        with self._lock:
            stragglers += self._pressure.pop(spec.job_id, 0)
        rate = scraped.get("step_rate")
        per_pod = (rate / world) if (rate and world) else 1.0
        stats = {
            "world": world,
            "per_pod_rate": per_pod,
            "goodput_ratio": scraped.get("goodput_ratio", 1.0),
            "gns": scraped.get("gns"),
            "stragglers": stragglers,
        }
        if self._stats_override is not None:
            try:
                override = self._stats_override(spec.job_id)
            except Exception:  # noqa: BLE001 — a drill hook must not stop the loop
                override = None
            if override:
                stats.update(override)
        return scale_decide.JobStats(**stats)

    def _mem_cap(self, job_id: str) -> Optional[int]:
        """The memory plane's fit verdict for one job, in pods: the
        largest ``mem/plan/{world}`` whose compile-time plan fits its
        own stamped device limit minus ``EDL_MEM_MARGIN`` (plan worlds
        count processes — divided down by ``procs_per_pod``). None when
        no judgeable plan is published: unknown never gates."""
        try:
            plans = obs_memory.read_plans(self.client, job_id)
        except Exception:  # noqa: BLE001 — store blip reads as unknown
            return None
        cap = obs_memory.fit_cap(plans)
        if cap is None:
            return None
        return cap // self.procs_per_pod

    # -- alert hook (Monitor on_fire registry) -----------------------------

    def alert_hook(self, job_id: str) -> Callable:
        """A ``(rule, doc)`` callable for :meth:`Monitor.add_on_fire`
        bound to one job: pressure-relevant firings count against the
        job's next allocation and wake the loop early."""

        def _hook(rule, doc) -> None:
            self.on_alert(rule, doc, job_id=job_id)

        return _hook

    def on_alert(self, rule, doc, job_id: Optional[str] = None) -> None:
        name = getattr(rule, "name", str(rule))
        if name not in _PRESSURE_RULES:
            return
        job = job_id if job_id is not None else self.jobs[0].job_id
        with self._lock:
            self._pressure[job] = self._pressure.get(job, 0) + 1
        self._wake.set()

    # -- deciding + acting -------------------------------------------------

    def _pool_capacity(self, actuals: Dict[str, int]) -> int:
        cap = self._capacity
        if callable(cap):
            cap = cap()
        if cap is None:
            cap = sum(actuals.values())
        return int(cap)

    def poll_once(self, now: Optional[float] = None) -> List[scale_decide.Decision]:
        """One sense->decide->act sweep; returns the decisions *acted on*
        (published to the store) this sweep."""
        now = time.time() if now is None else now
        with self._lock:
            jobs = list(self.jobs)
        complete = {j.job_id for j in jobs if self._job_complete(j.job_id)}
        jobs = [j for j in jobs if j.job_id not in complete]
        if not jobs:
            return []
        stats = {j.job_id: self._job_stats(j, now) for j in jobs}
        actuals = {job: s.world for job, s in stats.items()}
        capacity = self._pool_capacity(actuals)
        mem_caps = {j.job_id: self._mem_cap(j.job_id) for j in jobs}

        def _arb_max(j: JobSpec) -> int:
            # deprioritize unfit demand at the arbiter too: pods the fit
            # check says this job cannot hold go to jobs that can. The
            # cap never bites below the gang floor or the live world —
            # decide_world owns refusal, the arbiter only splits.
            mc = mem_caps[j.job_id]
            if mc is None:
                return j.max_world
            return max(j.min_world, stats[j.job_id].world, min(j.max_world, mc))

        demands = [
            scale_arbiter.JobDemand(
                job_id=j.job_id,
                min_world=j.min_world,
                max_world=_arb_max(j),
                priority=j.priority,
                weight=j.weight,
                stats=stats[j.job_id],
                params=self.params,
            )
            for j in jobs
        ]
        alloc = scale_arbiter.allocate(demands, capacity)
        # counterfactual allocation with the fit clamp lifted: _arb_max
        # shrinks a gated job's DEMAND, so the pods it cannot hold go to
        # other jobs — but that also means the allocation decide_world
        # sees may already end at the fit ceiling, hiding the gate
        # (hi == hi_raw: no mem_unfit cause, no trace). The ungated
        # re-run tells memory-bound apart from pool-bound.
        gated = [
            j for j in jobs
            if mem_caps[j.job_id] is not None and _arb_max(j) < j.max_world
        ]
        if gated:
            free_alloc = scale_arbiter.allocate(
                [dataclasses.replace(dm, max_world=j.max_world)
                 for dm, j in zip(demands, jobs)],
                capacity,
            )
        else:
            free_alloc = alloc
        decisions: Dict[str, scale_decide.Decision] = {}
        for j in jobs:
            decisions[j.job_id] = scale_decide.decide_world(
                stats[j.job_id],
                alloc[j.job_id],
                j.min_world,
                j.max_world,
                self.params,
                last=self._last.get(j.job_id),
                now=now,
                mem_cap=mem_caps[j.job_id],
            )
        gated_ids = {j.job_id for j in gated}
        for j in jobs:
            job = j.job_id
            d = decisions[job]
            cause = d.cause
            if not cause.startswith("mem_unfit") and job in gated_ids:
                # the arbiter absorbed the gate upstream: would the
                # model have taken more pods than the fit cap let the
                # arbiter offer? Compare against the UNGATED allocation.
                want_free = scale_decide.best_world(
                    j.min_world,
                    min(j.max_world, free_alloc[job]),
                    self.params,
                    stats[job],
                )
                if want_free > d.target:
                    cause = (
                        "mem_unfit: grow to %d withheld by the arbiter "
                        "fit clamp (largest fitting plan: %d pods)"
                        % (want_free, mem_caps[job])
                    )
            if cause.startswith("mem_unfit"):
                # every fit-gated decision leaves a trace, acted or not
                # (a refusal is a HOLD and never reaches _act/the store)
                self._m_unfit.inc()
                fields = dict(
                    job=job, kind=d.kind, target=d.target,
                    world=stats[job].world, cause=cause,
                )
                if self._recorder is not None:
                    self._recorder.record("mem_unfit", fsync=True, **fields)
                else:
                    obs_events.record("mem_unfit", fsync=True, **fields)
        # targets this sweep wants in force (acted kinds only), gang-gated
        want = {
            job: d.target
            for job, d in decisions.items()
            if d.kind != scale_decide.HOLD
        }
        released = scale_arbiter.release_targets(want, actuals)
        acted: List[scale_decide.Decision] = []
        for job in sorted(released):
            d = decisions[job]
            if self._published.get(job) == d.target:
                continue  # already in force — no seq churn, no re-publish
            acted.append(self._act(job, d, stats[job], now))
        deferred = sorted(set(want) - set(released))
        if deferred:
            logger.info(
                "gang sequencing: grow deferred for %s (shrinks in flight)",
                ",".join(deferred),
            )
        if self._tracer is not None and self._trace_path and acted:
            try:
                self._tracer.export(self._trace_path)
            except OSError as exc:
                logger.warning("scaler trace export failed: %s", exc)
        return acted

    def _act(
        self,
        job_id: str,
        decision: scale_decide.Decision,
        stats: scale_decide.JobStats,
        now: float,
    ) -> scale_decide.Decision:
        with self._lock:
            # add_job() publishes a queued target from the caller's
            # thread while the sweep loop acts — seq must stay unique
            self._seq += 1
            seq = self._seq
        decision = dataclasses.replace(decision, seq=seq, job_id=job_id, ts=now)
        ctx = obs_trace.op_context("scale", str(seq))
        if self._tracer is not None:
            # the deterministic decision root every reconcile segment
            # parents to — recorded on OUR tracer, not the global one
            self._tracer.record(
                "op:scale", time.monotonic(), 0.0,
                op="scale", op_key=str(seq), root=True,
                trace_id=ctx.trace_id, span_id=ctx.span_id,
                job=job_id, kind=decision.kind, target=decision.target,
            )
        fields = dict(
            trace_id=ctx.trace_id, seq=seq, job=job_id,
            kind=decision.kind, target=decision.target,
            world=stats.world, cause=decision.cause,
            score=round(decision.score, 4),
        )
        if self._recorder is not None:
            self._recorder.record("scale_decision", fsync=True, **fields)
        else:
            obs_events.record("scale_decision", fsync=True, **fields)
        target_doc = {
            "pods": decision.target,
            "seq": seq,
            "cause": decision.cause,
            "ts": now,
        }
        decision_doc = dict(
            target_doc,
            kind=decision.kind,
            world=stats.world,
            score=round(decision.score, 4),
            trace_id=ctx.trace_id,
            job=job_id,
        )
        try:
            reg = self._registries[job_id]
            reg.set_permanent(
                SCALE_SERVICE, TARGET_KEY, json.dumps(target_doc).encode()
            )
            reg.set_permanent(
                SCALE_SERVICE, DECISION_KEY, json.dumps(decision_doc).encode()
            )
        except Exception as exc:  # noqa: BLE001 — store blip: retry next sweep
            logger.warning("scale target for %s not published: %s", job_id, exc)
            return decision
        self._published[job_id] = decision.target
        self._last[job_id] = decision
        self._m_decisions.inc(kind=decision.kind)
        self._m_target.set(decision.target, job=job_id)
        logger.info(
            "scale decision #%d %s: %s %d -> %d (%s)",
            seq, job_id, decision.kind, stats.world, decision.target,
            decision.cause,
        )
        return decision

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="edl-scaler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — the loop must survive a bad sweep
                logger.warning("scaler sweep failed: %s", exc)
            self._wake.wait(self.interval)
            self._wake.clear()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._tracer is not None and self._trace_path:
            try:
                self._tracer.export(self._trace_path)
            except OSError as exc:
                logger.warning("scaler trace export failed: %s", exc)
        if self._owns_client and self.client is not None:
            try:
                self.client.close()
            except Exception:  # noqa: BLE001
                pass
