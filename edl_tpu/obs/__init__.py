"""Unified observability layer: metrics + spans + HTTP endpoints.

Three planes, one package:

- :mod:`edl_tpu.obs.metrics` — process-local registry of counters,
  gauges and fixed-bucket histograms (``edl_<component>_<name>_<unit>``
  naming, lint-enforced);
- :mod:`edl_tpu.obs.trace` — ring-buffer span tracer exporting Chrome
  trace-event JSON per process (``EDL_TRACE_DIR``), merged across the
  job by :mod:`edl_tpu.obs.merge`;
- :mod:`edl_tpu.obs.http` — ``/metrics`` (Prometheus text) and
  ``/healthz`` (JSON) served from a daemon thread on every long-lived
  process (``EDL_OBS_PORT``), endpoints registered in the coordination
  store so ``tools/edl_top.py`` discovers every scrape target from the
  store alone.
"""

from edl_tpu.obs.metrics import (
    DURATION_BUCKETS,
    METRIC_NAME_RE,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    GaugeBinding,
    Histogram,
    MetricsRegistry,
    bind_gauges,
    counter,
    default_registry,
    gauge,
    histogram,
)
from edl_tpu.obs.trace import SpanTracer, get_tracer, span
from edl_tpu.obs.http import (
    ObsServer,
    discover_endpoints,
    fetch_healthz,
    fetch_metrics,
    register_endpoint,
    start_from_env,
)

__all__ = [
    "DURATION_BUCKETS",
    "METRIC_NAME_RE",
    "SIZE_BUCKETS",
    "Counter",
    "Gauge",
    "GaugeBinding",
    "Histogram",
    "MetricsRegistry",
    "ObsServer",
    "bind_gauges",
    "SpanTracer",
    "counter",
    "default_registry",
    "discover_endpoints",
    "fetch_healthz",
    "fetch_metrics",
    "gauge",
    "get_tracer",
    "histogram",
    "register_endpoint",
    "span",
    "start_from_env",
]
