"""Unified observability layer: metrics + spans + HTTP endpoints.

Three planes, one package:

- :mod:`edl_tpu.obs.metrics` — process-local registry of counters,
  gauges and fixed-bucket histograms (``edl_<component>_<name>_<unit>``
  naming, lint-enforced);
- :mod:`edl_tpu.obs.trace` — ring-buffer span tracer exporting Chrome
  trace-event JSON per process (``EDL_TRACE_DIR``), merged across the
  job by :mod:`edl_tpu.obs.merge`;
- :mod:`edl_tpu.obs.http` — ``/metrics`` (Prometheus text) and
  ``/healthz`` (JSON) served from a daemon thread on every long-lived
  process (``EDL_OBS_PORT``), endpoints registered in the coordination
  store so ``tools/edl_top.py`` discovers every scrape target from the
  store alone;
- :mod:`edl_tpu.obs.events` — the crash-safe flight recorder
  (``EDL_FLIGHT_DIR``): append-only JSONL ring segments, one series per
  process, fsync'd on state transitions, survives SIGKILL;
- :mod:`edl_tpu.obs.goodput` — the per-process goodput ledger
  classifying every second of wall-clock into
  train/compile/data_wait/ckpt_save/ckpt_restore/restage/drain/stalled/
  down (``edl_goodput_seconds_total{state,cause}`` +
  ``edl_goodput_ratio``), merged job-wide by ``tools/edl_timeline.py``;
- :mod:`edl_tpu.obs.monitor` — the monitor plane: scrape-and-retain
  time series (``EDL_MONITOR_DIR`` ring segments), an SLO rule engine
  (threshold / rate / quantile-staleness / absence / restart detection
  with firing->resolved hysteresis), and alert records published to the
  store's ``alerts/{rule}`` keyspace (daemon:
  ``python -m tools.edl_monitord``);
- :mod:`edl_tpu.obs.profile` — the profiling plane: the roofline/peak
  cost model (shared with ``bench.py``), live windowed-MFU / roofline /
  HBM gauges per train stage, store-driven on-demand ``jax.profiler``
  capture windows publishing ``profile/result/{pod}``, and the
  monitor's alert-triggered auto-capture action (CLI:
  ``python -m tools.edl_profile``);
- :mod:`edl_tpu.obs.archive` — the cross-run plane: every run (chaos
  scenario, bench, harness job) harvested into an indexed bundle under
  ``EDL_RUN_ARCHIVE`` with a manifest, env-knob snapshot, and scalar
  rollups, one crash-safe ``runs/index.jsonl`` line per run;
- :mod:`edl_tpu.obs.regress` — the regression sentinel: a declarative
  per-metric table (direction / tolerance / min-samples) judged against
  a rolling baseline of same-``(kind, backend, world)`` archived runs
  (CLI: ``python -m tools.edl_report`` — list/trend/diff/check).
"""

from edl_tpu.obs.metrics import (
    DURATION_BUCKETS,
    METRIC_NAME_RE,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    GaugeBinding,
    Histogram,
    MetricsRegistry,
    bind_gauges,
    counter,
    default_registry,
    gauge,
    histogram,
    histogram_quantile,
)
from edl_tpu.obs.trace import SpanTracer, get_tracer, span
from edl_tpu.obs.events import FlightRecorder, get_recorder, read_segments
from edl_tpu.obs import goodput
from edl_tpu.obs import monitor
from edl_tpu.obs import profile
from edl_tpu.obs import archive
from edl_tpu.obs import regress
from edl_tpu.obs.http import (
    ObsServer,
    discover_endpoints,
    fetch_healthz,
    fetch_metrics,
    register_endpoint,
    start_from_env,
)

__all__ = [
    "DURATION_BUCKETS",
    "METRIC_NAME_RE",
    "SIZE_BUCKETS",
    "Counter",
    "FlightRecorder",
    "archive",
    "regress",
    "Gauge",
    "GaugeBinding",
    "Histogram",
    "MetricsRegistry",
    "ObsServer",
    "goodput",
    "bind_gauges",
    "SpanTracer",
    "counter",
    "default_registry",
    "discover_endpoints",
    "fetch_healthz",
    "fetch_metrics",
    "gauge",
    "get_recorder",
    "get_tracer",
    "histogram",
    "histogram_quantile",
    "monitor",
    "profile",
    "read_segments",
    "register_endpoint",
    "span",
    "start_from_env",
]
