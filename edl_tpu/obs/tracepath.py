"""Cross-process trace stitching and critical-path extraction.

The span tracer (:mod:`edl_tpu.obs.trace`) exports one Chrome trace per
process; with propagation armed, spans carry ``trace_id``/``span_id``/
``parent_id`` linkage and job-level operations (restage, drain, store
failover) share DETERMINISTIC trace ids derived from keys every
participant knows (the stage token, the pod id). This module is the read
side: load a run directory's exports, stitch the cross-process parent/
child graph per trace, and extract the **critical path** of each
operation — the ordered, non-overlapping sequence of segments (with the
process that owned each one) that accounts for the operation's
wall-clock, plus the untraced gaps in between.

Consumers: ``tools/edl_trace.py`` (the CLI), ``tools/edl_timeline.py``
(op overlay on the postmortem timeline), and the chaos plane's
``critical_path_traced`` invariant, which also cross-checks the stitched
path against the goodput ledger's restage accounting
(:func:`goodput_compare`).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from edl_tpu.utils.log import get_logger

logger = get_logger("obs.tracepath")

# a segment shorter than this cannot anchor a path slice (zero-duration
# markers — op roots, instants promoted to spans — are kept as events
# but never claim wall-clock)
_MIN_DUR_S = 1e-6


@dataclasses.dataclass
class Segment:
    """One linked span, timestamps in epoch SECONDS."""

    name: str
    component: str
    t0: float
    t1: float
    trace_id: str
    span_id: str
    parent_id: str
    args: Dict
    pid: int = 0

    @property
    def dur(self) -> float:
        return max(0.0, self.t1 - self.t0)


@dataclasses.dataclass
class PathSeg:
    """One slice of the critical path; ``segment`` None = untraced gap."""

    t0: float
    t1: float
    segment: Optional[Segment]

    @property
    def dur(self) -> float:
        return max(0.0, self.t1 - self.t0)


@dataclasses.dataclass
class OpTrace:
    """One stitched operation trace."""

    trace_id: str
    op: str                      # "" when the trace has no named root
    op_key: str
    root_id: str                 # span id segments parent to (derived ok)
    root_args: Dict
    segments: List[Segment]
    orphans: List[Segment]       # parent not resolvable inside the trace
    t0: float = 0.0
    t1: float = 0.0

    @property
    def processes(self) -> List[str]:
        return sorted({s.component for s in self.segments})

    @property
    def complete(self) -> bool:
        """A restage/drain/scale trace that reached its closing
        segment (for a scale op: the leader's reconcile publish)."""
        return any(s.name == "first_step" for s in self.segments) or (
            self.op == "drain"
            and any(s.name in ("ckpt_save", "drained") for s in self.segments)
        ) or (
            self.op == "scale"
            and any(s.name == "reconcile" for s in self.segments)
        )

    def first_step_t0(self) -> Optional[float]:
        hits = [s.t0 for s in self.segments if s.name == "first_step"]
        return min(hits) if hits else None


# -- loading ------------------------------------------------------------------


def discover_trace_files(run_dir: str) -> List[str]:
    """Every ``*.trace.json`` under ``run_dir``, two levels deep (same
    convention as edl-timeline's artifact discovery)."""
    out: List[str] = []
    for depth in ("", "*", os.path.join("*", "*")):
        out.extend(
            sorted(glob.glob(os.path.join(run_dir, depth, "*.trace.json")))
        )
    # a dir passed directly also works when it IS the trace dir
    return sorted(set(out))


def load_spans(paths: Iterable[str]) -> List[Segment]:
    """Linked spans from per-process trace exports. Unlinked spans (no
    trace args) are skipped — they belong to the flat timeline view.
    Files that fail to parse are skipped with a warning (a torn export
    from a killed worker must not hide the others)."""
    spans: List[Segment] = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
            events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
        except (OSError, ValueError) as exc:
            logger.warning("skipping %s: %s", path, exc)
            continue
        comp_by_pid: Dict = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                comp_by_pid[ev.get("pid")] = (ev.get("args") or {}).get(
                    "name", ""
                )
        label = os.path.basename(path).replace(".trace.json", "")
        for ev in events:
            if ev.get("ph") != "X":
                continue
            args = ev.get("args") or {}
            tid = args.get("trace_id")
            if not tid:
                continue
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            t0 = float(ts) / 1e6
            dur = float(ev.get("dur", 0.0) or 0.0) / 1e6
            spans.append(
                Segment(
                    name=str(ev.get("name", "?")),
                    component=str(comp_by_pid.get(ev.get("pid")) or label),
                    t0=t0,
                    t1=t0 + dur,
                    trace_id=str(tid),
                    span_id=str(args.get("span_id", "")),
                    parent_id=str(args.get("parent_id", "")),
                    args={
                        k: v
                        for k, v in args.items()
                        if k not in ("trace_id", "span_id", "parent_id")
                    },
                    pid=int(ev.get("pid", 0) or 0),
                )
            )
    return spans


def load_run(run_dir: str) -> List[Segment]:
    return load_spans(discover_trace_files(run_dir))


# -- stitching ----------------------------------------------------------------


def extract_ops(
    spans: Iterable[Segment], op: Optional[str] = None
) -> List[OpTrace]:
    """Group linked spans by trace id and stitch each into an
    :class:`OpTrace`; ``op`` filters to one operation name. Traces whose
    root anchor was never exported (its process died first) still
    stitch: the root id is recovered as the dominant unresolved parent,
    and the op name from any ``op=`` segment arg."""
    by_trace: Dict[str, List[Segment]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    out: List[OpTrace] = []
    for tid, segs in sorted(by_trace.items()):
        root = next(
            (s for s in segs if s.args.get("root") in (True, "True")), None
        )
        ids = {s.span_id for s in segs if s.span_id}
        if root is not None:
            root_id = root.span_id
            op_name = str(root.args.get("op", ""))
            op_key = str(root.args.get("op_key", ""))
            root_args = dict(root.args)
        else:
            # root never exported: the single most common parent id that
            # no segment owns is the anchor; ties/others are orphans
            unknown: Dict[str, int] = {}
            for s in segs:
                if s.parent_id and s.parent_id not in ids:
                    unknown[s.parent_id] = unknown.get(s.parent_id, 0) + 1
            root_id = max(unknown, key=lambda k: unknown[k]) if unknown else ""
            op_name = next(
                (str(s.args["op"]) for s in segs if s.args.get("op")), ""
            )
            op_key = ""
            root_args = {}
        body = [s for s in segs if s is not root]
        orphans = [
            s
            for s in body
            if s.parent_id and s.parent_id not in ids and s.parent_id != root_id
        ]
        if op is not None and op_name != op:
            continue
        timed = [s for s in body if s.dur >= _MIN_DUR_S] or body
        t0 = min(
            [s.t0 for s in timed] + ([root.t0] if root is not None else [])
        ) if timed or root is not None else 0.0
        t1 = max([s.t1 for s in timed], default=t0)
        out.append(
            OpTrace(
                trace_id=tid,
                op=op_name,
                op_key=op_key,
                root_id=root_id,
                root_args=root_args,
                segments=sorted(body, key=lambda s: (s.t0, s.t1)),
                orphans=orphans,
                t0=t0,
                t1=t1,
            )
        )
    out.sort(key=lambda o: o.t0)
    return out


def _depths(ot: OpTrace) -> Dict[str, int]:
    """Span depth below the root (unknown parentage = depth 1): the
    critical path prefers the DEEPEST active span — a restore inside an
    init window names the restore, not the window."""
    by_id = {s.span_id: s for s in ot.segments if s.span_id}
    depth: Dict[str, int] = {}

    def walk(span_id: str, seen) -> int:
        if span_id in depth:
            return depth[span_id]
        s = by_id.get(span_id)
        if s is None or span_id in seen:
            return 0
        seen.add(span_id)
        if not s.parent_id or s.parent_id == ot.root_id:
            d = 1
        else:
            d = 1 + walk(s.parent_id, seen)
        depth[span_id] = d
        return d

    for s in ot.segments:
        if s.span_id:
            walk(s.span_id, set())
    return depth


def critical_path(ot: OpTrace) -> List[PathSeg]:
    """The operation's wall-clock as an ordered, non-overlapping slice
    sequence: at every instant the deepest active segment owns the
    slice; instants nobody covers are explicit gaps. Slice boundaries
    are the segments' own endpoints, so the result partitions
    ``[ot.t0, ot.t1]`` exactly."""
    segs = [s for s in ot.segments if s.dur >= _MIN_DUR_S]
    if not segs:
        return []
    depth = _depths(ot)
    bounds = sorted({ot.t0, ot.t1} | {s.t0 for s in segs} | {s.t1 for s in segs})
    path: List[PathSeg] = []
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        active = [s for s in segs if s.t0 <= mid < s.t1]
        owner = (
            max(active, key=lambda s: (depth.get(s.span_id, 1), s.t0))
            if active
            else None
        )
        if path and path[-1].segment is owner:
            path[-1].t1 = b
        else:
            path.append(PathSeg(a, b, owner))
    return path


def covered_seconds(path: List[PathSeg]) -> float:
    return sum(p.dur for p in path if p.segment is not None)


# -- goodput cross-check ------------------------------------------------------


def goodput_compare(
    ot: OpTrace, flight_events: List[Dict]
) -> Optional[Dict]:
    """Cross-check a restage trace against the goodput ledger.

    Over the pre-first-step window (everything before the closing
    segment is restage cost by definition), the critical path's covered
    seconds should account for the ledger's restage lane: the window
    minus whatever the ``(component, pid)`` lanes that contributed
    segments to this trace spent productively (train/data_wait). Only
    matched lanes count, so a concurrently draining OTHER pod (its own
    drain trace) never skews the comparison. Returns ``{"window_s",
    "path_s", "lane_s", "delta_s"}`` or None when either side lacks
    evidence."""
    from edl_tpu.obs import goodput as obs_goodput

    fs = ot.first_step_t0()
    t1 = fs if fs is not None else ot.t1
    if not flight_events or t1 <= ot.t0:
        return None
    keys = {(s.component, s.pid) for s in ot.segments}
    productive: List[Tuple[float, float]] = []
    found = False
    for lane_key, spans in obs_goodput.process_intervals(flight_events).items():
        if lane_key not in keys:
            continue
        found = True
        for a, b, state in spans:
            if state not in ("train", "data_wait"):
                continue
            a2, b2 = max(a, ot.t0), min(b, t1)
            if b2 > a2:
                productive.append((a2, b2))
    if not found:
        return None
    # the restage lane = window MINUS the union of the matched lanes'
    # productive (train/data_wait) slices: inside a restage window, any
    # instant no participating worker was productively training is
    # restage cost — including the pre-init boot window the ledger
    # cannot record (the process did not exist yet; the trace's
    # worker_boot segment from the spawn stamp covers exactly that).
    # UNION, not sum, so concurrently restaging workers count once.
    productive.sort()
    prod = 0.0
    cur_end = None
    for a, b in productive:
        if cur_end is None or a > cur_end:
            prod += b - a
            cur_end = b
        elif b > cur_end:
            prod += b - cur_end
            cur_end = b
    lane = max(0.0, (t1 - ot.t0) - prod)
    path_s = sum(
        min(p.t1, t1) - p.t0
        for p in critical_path(ot)
        if p.segment is not None and p.t0 < t1
    )
    return {
        "window_s": t1 - ot.t0,
        "path_s": path_s,
        "lane_s": lane,
        "delta_s": path_s - lane,
    }


# -- rendering ----------------------------------------------------------------


def render_op(ot: OpTrace, origin: Optional[float] = None) -> str:
    """One operation as a human table: header, per-segment rows with the
    owning process, explicit gaps, coverage footer."""
    origin = ot.t0 if origin is None else origin
    head = "op=%s%s trace=%s  window %.3fs  processes: %s" % (
        ot.op or "(unnamed)",
        (" key=%s" % ot.op_key[:8]) if ot.op_key else "",
        ot.trace_id,
        ot.t1 - ot.t0,
        ", ".join(ot.processes) or "-",
    )
    lines = [head]
    if ot.root_args:
        interesting = {
            k: v for k, v in sorted(ot.root_args.items()) if k != "root"
        }
        if interesting:
            lines.append(
                "  root: %s"
                % " ".join("%s=%s" % kv for kv in interesting.items())
            )
    path = critical_path(ot)
    lines.append(
        "  %10s %9s  %-18s %s" % ("t+", "dur", "process", "segment")
    )
    for p in path:
        if p.segment is None:
            lines.append(
                "  %+10.3fs %8.3fs  %-18s %s"
                % (p.t0 - origin, p.dur, "-", "(untraced gap)")
            )
        else:
            extra = " ".join(
                "%s=%s" % (k, v)
                for k, v in sorted(p.segment.args.items())
                if k not in ("root", "op")
            )
            lines.append(
                "  %+10.3fs %8.3fs  %-18s %s%s"
                % (
                    p.t0 - origin,
                    p.dur,
                    p.segment.component,
                    p.segment.name,
                    (" [%s]" % extra) if extra else "",
                )
            )
    window = ot.t1 - ot.t0
    cov = covered_seconds(path)
    lines.append(
        "  critical path %.3fs of %.3fs window (%.0f%% traced), %d "
        "segment(s), %d orphan(s)%s"
        % (
            cov,
            window,
            100.0 * cov / window if window > 0 else 0.0,
            sum(1 for p in path if p.segment is not None),
            len(ot.orphans),
            "" if ot.complete else "  [INCOMPLETE]",
        )
    )
    return "\n".join(lines)


def to_json(ot: OpTrace) -> Dict:
    path = critical_path(ot)
    return {
        "op": ot.op,
        "op_key": ot.op_key,
        "trace_id": ot.trace_id,
        "t0": ot.t0,
        "t1": ot.t1,
        "processes": ot.processes,
        "complete": ot.complete,
        "orphans": len(ot.orphans),
        "covered_s": covered_seconds(path),
        "path": [
            {
                "t0": p.t0,
                "t1": p.t1,
                "dur": p.dur,
                "name": p.segment.name if p.segment else None,
                "component": p.segment.component if p.segment else None,
            }
            for p in path
        ],
        "segments": [
            {
                "name": s.name,
                "component": s.component,
                "t0": s.t0,
                "t1": s.t1,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
            }
            for s in ot.segments
        ],
    }
