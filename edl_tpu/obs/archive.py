"""Run archive: every run becomes a comparable, gated artifact.

Five observability planes (metrics, flight recorder, monitor, profiling,
tracing) answer questions about ONE run; nothing answered questions
ACROSS runs — chaos scenarios, benches, and real jobs scattered flight
segments, trace exports, monitor series, and loose ``bench_results``
JSON with no index, no baseline, and no regression gate. This module is
the cross-run plane: a :class:`RunArchive` harvests a finished run's
artifacts into one bundle under an archive root::

    {root}/{kind}-{job_id}-{seq}/
        run.json            manifest: kind/backend/world/seed/git-sha,
                            env-knob snapshot, scalar rollups,
                            invariant verdicts, artifact inventory
        flight/             *.flight.jsonl segments (crash-safe black box)
        traces/             *.trace.json per-process span exports
        monitor/            *.series.jsonl retained monitor samples
        chaos.log           chaos injection ledger
        bench.json          the bench tool's own result document
        invariants.json     chaos invariant verdicts
    {root}/index.jsonl      one append-only line per archived run

The index line rides the :class:`~edl_tpu.obs.events.FlightRecorder`
write discipline (``stable_path`` mode: one ``O_APPEND`` write,
fsync'd, torn tail skipped by the reader) so a crash mid-archive costs
at most the one line it interrupted — the bundle directory stays, the
next ``edl_report --list`` just doesn't show it.

**Rollups** turn artifacts into comparable scalars at archive time:
goodput ratio and per-state lane seconds from the flight segments,
traced restage critical-path seconds from the span exports, checkpoint
restore tier counts from the tier-labeled flight records, and
bench-specific scalars (resize downtime/compile split, store put p99,
MFU, restore tiers) from the bench JSON. The regression sentinel
(:mod:`edl_tpu.obs.regress`) and ``tools/edl_report.py`` consume ONLY
the index rows — listing, trending, diffing and gating never re-parse
a bundle unless attribution is asked for (``--diff``).

Env contract:

    EDL_RUN_ARCHIVE   archive root directory; unset/empty/``0``
                      disables archiving (``1`` means "the default
                      root" for callers that have one). The chaos
                      scenario runner archives unconditionally into
                      ``{workdir}/runs`` when the knob is unset — every
                      scenario run must leave a bundle (the
                      ``run_archived`` invariant).
"""

from __future__ import annotations

import glob
import json
import os
import re
import shutil
import subprocess
import time
from typing import Dict, List, Optional

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import goodput as obs_goodput
from edl_tpu.obs import tracepath
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.archive")

ENV_ROOT = "EDL_RUN_ARCHIVE"
INDEX_NAME = "index.jsonl"
MANIFEST_NAME = "run.json"
SCHEMA = 1

_SLUG_RE = re.compile(r"[^A-Za-z0-9._-]+")


def archive_root(
    default: Optional[str] = None, env: Optional[Dict[str, str]] = None
) -> Optional[str]:
    """The armed archive root, or None when archiving is off.

    ``EDL_RUN_ARCHIVE`` names the root; ``0`` force-disables (the chaos
    rig sets it on its inner harnesses so the scenario-level archive is
    the only one); ``1`` means "the caller's default root". Unset falls
    back to ``default`` — callers that archive by default (the chaos
    runner, the TPU suite) pass one, opt-in callers (benches, the
    harness) pass None. ``env`` lets a harness consult the environment
    it hands its pods instead of its own."""
    if env is None:
        root = (os.environ.get("EDL_RUN_ARCHIVE") or "").strip()
    else:
        root = (env.get(ENV_ROOT) or "").strip()
    if root == "0":
        return None
    if root == "1":
        return default or os.path.join(os.getcwd(), "runs")
    if root == "":
        return default
    return root


def knob_snapshot(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Every ``EDL_*`` knob visible to the run — the process env plus
    whatever the harness injected into its pods. The knob-snapshot lint
    (tests/test_report.py) cross-checks these names against the
    generated DESIGN.md knob catalogue, the same registry edl-lint's
    ``env-registry`` pass maintains."""
    knobs = {k: v for k, v in os.environ.items() if k.startswith("EDL_")}
    for k, v in (extra or {}).items():
        if k.startswith("EDL_"):
            knobs[k] = v
    return dict(sorted(knobs.items()))


def git_sha(repo_dir: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=repo_dir, capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def backend_guess(env: Optional[Dict[str, str]] = None) -> str:
    """cpu/tpu/... from ``JAX_PLATFORMS`` without importing jax (the
    archiver often runs after the job, in a process that never touched
    a device)."""
    src = os.environ if env is None else env
    plat = (src.get("JAX_PLATFORMS") or "").strip().split(",")[0]
    return plat or "cpu"


# -- rollups ------------------------------------------------------------------


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def rollups_from_flight(events: List[Dict]) -> Dict[str, float]:
    """Goodput-ledger scalars from merged flight events: the job-level
    wall-clock attribution collapsed to per-state lane seconds plus the
    headline goodput ratio, and checkpoint restore tier counts from the
    tier-labeled ``ckpt_restore`` records."""
    if not events:
        return {}
    out: Dict[str, float] = dict(obs_goodput.job_goodput(events)["rollup"])
    tiers: Dict[str, int] = {}
    for ev in events:
        if ev.get("event") == "ckpt_restore" and ev.get("tier"):
            tier = str(ev["tier"])
            tiers[tier] = tiers.get(tier, 0) + 1
    for tier, n in sorted(tiers.items()):
        out["ckpt_restore_%s" % tier] = n
    return out


def last_restage_op(spans):
    """The newest SUBSTANTIVE restage operation in a run's spans (the
    op --diff and the trace rollups both judge): completed preferred,
    degenerate zero-wall ops (root exported, body lost with its
    process) never shadow a real one. Returns ``(op, total_count)``,
    op None when the run traced no restage."""
    ops = [ot for ot in tracepath.extract_ops(spans) if ot.op == "restage"]
    if not ops:
        return None, 0
    done = [ot for ot in ops if ot.complete] or ops
    timed = [ot for ot in done if ot.t1 - ot.t0 > 0.01] or done
    return timed[-1], len(ops)


def rollups_from_traces(spans) -> Dict[str, float]:
    """Tracing-plane scalars: the critical-path seconds of the last
    substantive restage operation (the lane ``--diff`` attributes
    regressions to, segment by segment)."""
    ot, count = last_restage_op(spans)
    if ot is None:
        return {}
    path = tracepath.critical_path(ot)
    return {
        "traced_restage_s": round(tracepath.covered_seconds(path), 3),
        "traced_restage_wall_s": round(ot.t1 - ot.t0, 3),
        "traced_restages": count,
    }


_BENCH_SCALARS = (
    "mfu", "per_chip", "per_chip_loss_pct", "vs_baseline",
    "peer_restore_s", "durable_restore_s_raw", "durable_restore_s_modeled",
    "push_s", "save_s", "roofline_mfu_ceiling", "host_link_MBps",
    "serve_qps", "serve_p50_ms", "serve_p99_ms", "serve_shed_pct",
    "serve_hedge_ratio",
)


def rollups_from_bench(doc: Dict) -> Dict[str, float]:
    """Bench-result JSON collapsed to comparable scalars. Knows the
    in-tree shapes: the ``{"metric", "value"}`` headline convention,
    resize_bench's transition decomposition, store_bench's per-shard
    latency tables, ckpt_bench's tier timings, bench.py's MFU/roofline
    block — and degrades to the headline alone for anything else."""
    out: Dict[str, float] = {}
    if not isinstance(doc, dict):
        return out
    metric = doc.get("metric")
    if isinstance(metric, str) and metric and _num(doc.get("value")):
        key = metric
        if key.endswith("_unavailable"):
            key = key[: -len("_unavailable")]
        out[key] = float(doc["value"])
    for k in _BENCH_SCALARS:
        if _num(doc.get(k)):
            out[k] = float(doc[k])
    transitions = doc.get("transitions")
    if isinstance(transitions, list):
        def col(name):
            return [
                float(t[name]) for t in transitions
                if isinstance(t, dict) and _num(t.get(name))
            ]
        downs = col("downtime_s")
        if downs:
            out.setdefault("resize_downtime", max(downs))
        compiles = col("compile_s")
        if compiles:
            out["restage_compile_s"] = max(compiles)
        restores = col("restore_s")
        if restores:
            out["restage_restore_s"] = max(restores)
        misses = col("cache_misses")
        if misses:
            out["cache_misses"] = sum(misses)
    results = doc.get("results")
    if isinstance(results, list) and results and isinstance(results[-1], dict):
        last = results[-1]  # the headline config (store_bench convention)
        if _num(last.get("aggregate_puts_per_s")):
            out["store_puts_per_s"] = float(last["aggregate_puts_per_s"])
        p99s = [
            float(s["p99_ms"])
            for s in (last.get("client_put_ms_by_shard") or {}).values()
            if isinstance(s, dict) and _num(s.get("p99_ms"))
        ]
        if p99s:
            out["store_put_p99_ms"] = max(p99s)
        # store_bench --reads: the headline row is the standby-serving
        # lane (results[-1] by the same convention)
        if _num(last.get("aggregate_reads_per_s")):
            out["store_reads_per_s"] = float(last["aggregate_reads_per_s"])
        if _num(last.get("read_p99_ms")):
            out["store_read_p99_ms"] = float(last["read_p99_ms"])
    return out


# -- the archive itself -------------------------------------------------------


def _slug(text) -> str:
    return _SLUG_RE.sub("_", str(text)) or "run"


def _write_json(path: str, doc) -> None:
    """tmp -> fsync -> rename: a manifest is a durable artifact and must
    never be observable half-written (same discipline edl-lint's
    atomic-write pass enforces on durable-scope modules)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _copy_glob(pattern: str, dest_dir: str) -> int:
    n = 0
    for src in sorted(glob.glob(pattern)):
        try:
            if n == 0:
                os.makedirs(dest_dir, exist_ok=True)
            shutil.copy2(src, os.path.join(dest_dir, os.path.basename(src)))
            n += 1
        except OSError as exc:
            logger.warning("archive copy failed for %s: %s", src, exc)
    return n


def read_index(root: str) -> List[Dict]:
    """Index rows in append order; torn tail lines skipped."""
    rows = obs_events.read_records(os.path.join(root, INDEX_NAME))
    return [r for r in rows if r.get("kind")]


def find_bundle(root: str, name: str) -> Optional[str]:
    """Resolve a bundle by name under ``root`` or by direct path (a
    bundle dir, or its ``run.json``)."""
    for cand in (
        name,
        os.path.join(root, name) if root else None,
    ):
        if not cand:
            continue
        if os.path.isfile(cand) and os.path.basename(cand) == MANIFEST_NAME:
            return os.path.dirname(os.path.abspath(cand))
        if os.path.isdir(cand) and os.path.isfile(
            os.path.join(cand, MANIFEST_NAME)
        ):
            return cand
    return None


def load_manifest(bundle: str) -> Optional[Dict]:
    try:
        with open(os.path.join(bundle, MANIFEST_NAME)) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        logger.warning("unreadable manifest under %s: %s", bundle, exc)
        return None
    return doc if isinstance(doc, dict) else None


class RunArchive:
    """One archive root: bundle allocation + harvest + index append."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.index_path = os.path.join(root, INDEX_NAME)
        self._index: Optional[obs_events.FlightRecorder] = None

    def _index_recorder(self) -> obs_events.FlightRecorder:
        if self._index is None:
            self._index = obs_events.FlightRecorder(
                self.root, component="index", suffix=".jsonl",
                stable_path=self.index_path,
            )
        return self._index

    def read_index(self) -> List[Dict]:
        return read_index(self.root)

    def next_seq(self, kind: str, job_id: str) -> int:
        prefix = "%s-%s-" % (_slug(kind), _slug(job_id))
        seq = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for name in names:
            tail = name[len(prefix):]
            if name.startswith(prefix) and tail.isdigit():
                seq = max(seq, int(tail) + 1)
        return seq

    def append_row(self, row: Dict) -> None:
        """One crash-safe index line (the FlightRecorder write
        discipline under ``stable_path``)."""
        self._index_recorder().record("archived", fsync=True, **row)

    def archive(
        self,
        kind: str,
        job_id: str,
        backend: str = "cpu",
        world: Optional[int] = None,
        seed: Optional[int] = None,
        flight_dir: Optional[str] = None,
        trace_dir: Optional[str] = None,
        monitor_dir: Optional[str] = None,
        chaos_log: Optional[str] = None,
        bench: Optional[Dict] = None,
        invariants: Optional[List[Dict]] = None,
        rollups: Optional[Dict] = None,
        knobs: Optional[Dict[str, str]] = None,
        extra: Optional[Dict] = None,
        stale: bool = False,
        excluded: bool = False,
    ) -> str:
        """Harvest one run into a fresh bundle and index it; returns the
        bundle path. Explicit ``rollups`` win over derived ones."""
        seq = self.next_seq(kind, job_id)
        name = "%s-%s-%d" % (_slug(kind), _slug(job_id), seq)
        bundle = os.path.join(self.root, name)
        os.makedirs(bundle, exist_ok=True)

        artifacts: Dict[str, int] = {}
        if flight_dir:
            artifacts["flight_segments"] = _copy_glob(
                os.path.join(flight_dir, "*.flight.jsonl"),
                os.path.join(bundle, "flight"),
            )
        if trace_dir:
            artifacts["traces"] = _copy_glob(
                os.path.join(trace_dir, "*.trace.json"),
                os.path.join(bundle, "traces"),
            )
        if monitor_dir:
            artifacts["monitor_series"] = _copy_glob(
                os.path.join(monitor_dir, "*.series.jsonl"),
                os.path.join(bundle, "monitor"),
            )
        if chaos_log and os.path.isfile(chaos_log):
            try:
                shutil.copy2(chaos_log, os.path.join(bundle, "chaos.log"))
                artifacts["chaos_log"] = 1
            except OSError as exc:
                logger.warning("archive copy failed for %s: %s", chaos_log, exc)
        if bench is not None:
            _write_json(os.path.join(bundle, "bench.json"), bench)
            artifacts["bench"] = 1
        if invariants is not None:
            _write_json(os.path.join(bundle, "invariants.json"), invariants)
            artifacts["invariants"] = len(invariants)

        merged: Dict = {}
        flight_events: List[Dict] = []
        if artifacts.get("flight_segments"):
            flight_events = obs_events.read_segments(
                os.path.join(bundle, "flight")
            )
            merged.update(rollups_from_flight(flight_events))
        if artifacts.get("traces"):
            merged.update(
                rollups_from_traces(
                    tracepath.load_spans(
                        sorted(glob.glob(
                            os.path.join(bundle, "traces", "*.trace.json")
                        ))
                    )
                )
            )
        if bench is not None:
            merged.update(rollups_from_bench(bench))
        ok: Optional[bool] = None
        if invariants is not None:
            failed = sum(1 for r in invariants if not r.get("ok"))
            merged["invariants_total"] = len(invariants)
            merged["invariants_failed"] = failed
            ok = failed == 0
        if rollups:
            merged.update(rollups)

        manifest = {
            "schema": SCHEMA,
            "bundle": name,
            "kind": kind,
            "job_id": job_id,
            "seq": seq,
            "backend": backend,
            "world": world,
            "seed": seed,
            "git_sha": git_sha(),
            "ts": time.time(),
            "knobs": knobs if knobs is not None else knob_snapshot(),
            "rollups": merged,
            "ok": ok,
            "stale": bool(stale),
            "excluded": bool(excluded),
            "artifacts": artifacts,
        }
        if extra:
            manifest["extra"] = extra
        _write_json(os.path.join(bundle, MANIFEST_NAME), manifest)

        row = {
            "bundle": name,
            "kind": kind,
            "job_id": job_id,
            "seq": seq,
            "backend": backend,
            "world": world,
            "seed": seed,
            "git_sha": manifest["git_sha"],
            "ok": ok,
            "stale": bool(stale),
            "excluded": bool(excluded),
            "rollups": merged,
        }
        self.append_row(row)
        logger.info(
            "archived %s (%d rollups, artifacts: %s)",
            bundle, len(merged),
            ", ".join("%s=%s" % kv for kv in sorted(artifacts.items()))
            or "none",
        )
        return bundle


def maybe_archive_bench(
    kind: str,
    doc: Dict,
    job_id: Optional[str] = None,
    backend: Optional[str] = None,
    world: Optional[int] = None,
    seed: Optional[int] = None,
    flight_dir: Optional[str] = None,
    trace_dir: Optional[str] = None,
    root: Optional[str] = None,
    stale: bool = False,
    excluded: bool = False,
    default_root: Optional[str] = None,
) -> Optional[str]:
    """Bench-tool wiring: archive a result when ``EDL_RUN_ARCHIVE`` is
    armed, else no-op. Never raises — a broken archive must not fail the
    measurement that just finished."""
    root = root or archive_root(default=default_root)
    if not root:
        return None
    backend = backend or backend_guess()
    try:
        bundle = RunArchive(root).archive(
            kind,
            job_id or backend,
            backend=backend,
            world=world,
            seed=seed,
            flight_dir=flight_dir,
            trace_dir=trace_dir,
            bench=doc,
            stale=stale,
            excluded=excluded,
        )
    except Exception as exc:  # noqa: BLE001 — archive is best-effort here
        logger.warning("run archive failed: %s", exc)
        return None
    return bundle
