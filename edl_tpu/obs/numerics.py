"""Numerics observability plane: is the job still *learning*?

Every observer before this one watches the control plane — goodput
prices wall-clock, traces price RPCs, the checkpoint ledger prices
durability — but a resize that corrupts optimizer state, a bit-flipped
gradient, or silently diverged dp replicas is invisible until an
offline convergence run hours later. This module watches the *model*:

- :func:`device_bundle` — a pure-jnp scalar bundle fused into the
  jitted train step (loss, global grad norm, param norm, update ratio,
  non-finite element count, optional half-batch grad norms for the
  gradient-noise-scale estimate). Everything stays on device as 0-d
  f32 arrays; nothing here reads the host clock or environment.
- :class:`NumericsProbe` — the host half. It swaps the freshly
  computed bundle into a one-deep buffer every step and only
  device-transfers every ``EDL_NUMERICS_EVERY`` steps, and then it
  fetches the *previous* step's bundle — whose computation has had a
  full step to retire — so the probe never adds a sync stall to the
  hot path. Published values land as ``edl_train_*`` gauges, flight
  records (``numerics`` / ``nonfinite`` / ``loss_spike`` instants for
  ``edl-timeline``), a windowed gradient-noise-scale estimate
  (McCandlish et al., *An Empirical Model of Large-Batch Training*:
  the small-batch/large-batch norm trick over the two half-batch
  gradients the step already averaged), and a cross-replica parameter
  digest published through the store so ``edl_train_replica_divergence``
  reads the relative spread across dp replicas *at the same step*.
- the **resize continuity sentinel** — :func:`stamp_fingerprint` puts
  a ``{step, loss, param_norm}`` fingerprint into the checkpoint
  manifest at save, :func:`verify_fingerprint` re-derives the param
  norm at restore (a mismatched candidate is quarantined like any
  corrupt checkpoint), and :meth:`NumericsProbe.expect` asserts
  post-resume loss continuity within ``EDL_NUMERICS_LOSS_TOL`` —
  flight-recorded as ``numerics_resume`` so the chaos invariant
  ``numerics_continuous`` can gate worker-kill/preempt-drain drills.

Knobs: ``EDL_NUMERICS`` (``0`` disables the plane), ``EDL_NUMERICS_EVERY``
(device->host transfer cadence, steps), ``EDL_NUMERICS_GNS`` (``0``
skips the half-batch gradient pass), ``EDL_NUMERICS_FP_TOL``
(fingerprint param-norm relative tolerance), ``EDL_NUMERICS_LOSS_TOL``
(post-resume loss-continuity relative tolerance).
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.numerics")

#: reserved key the fused probe smuggles its bundle through in the train
#: step's metrics dict — the loop pops it before metrics aggregation
METRICS_KEY = "_numerics"

ENV_ENABLED = "EDL_NUMERICS"
ENV_EVERY = "EDL_NUMERICS_EVERY"
ENV_GNS = "EDL_NUMERICS_GNS"
ENV_FP_TOL = "EDL_NUMERICS_FP_TOL"
ENV_LOSS_TOL = "EDL_NUMERICS_LOSS_TOL"

DEFAULT_EVERY = 8
DEFAULT_FP_TOL = 1e-4       # fingerprint param-norm relative tolerance
DEFAULT_LOSS_TOL = 0.5      # post-resume loss-continuity relative tolerance

_GNS_WINDOW = 32            # (g2, s) pairs retained for the windowed GNS
_SPIKE_HISTORY = 64         # published losses retained for spike detection
_SPIKE_MIN_HISTORY = 6      # finite points required before a z is trusted
_SPIKE_Z = 4.0              # host-side twin of the loss-spike monitor rule
_DIGEST_SERVICE = "numerics"

# newest (step, device-bundle) any probe in this process has seen —
# fingerprint_for_save reads the loss out of it at checkpoint time (a
# save is already a sync point, so the one device_get is free)
_LATEST: Optional[Tuple[int, Dict[str, Any]]] = None
_LATEST_LOCK = threading.Lock()


def enabled() -> bool:
    return os.environ.get("EDL_NUMERICS", "1") != "0"


def _reset() -> None:
    """Forget cross-probe module state (tests)."""
    global _LATEST
    with _LATEST_LOCK:
        _LATEST = None


# -- device side (pure jnp: traced inside the jitted train step) ----------


def _inexact_leaves(tree) -> List[Any]:
    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]


def _sq_norm(tree) -> jnp.ndarray:
    """Global squared L2 norm over the inexact leaves, f32 accumulation."""
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def _nonfinite_count(tree) -> jnp.ndarray:
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum((~jnp.isfinite(leaf)).astype(jnp.float32))
    return total


def device_bundle(
    loss,
    grads,
    params,
    new_params,
    halves: Optional[Tuple[Any, Any]] = None,
    batch: Optional[int] = None,
) -> Dict[str, jnp.ndarray]:
    """The per-step scalar bundle, computed on device inside the jitted
    step: a dict of 0-d f32 arrays (plus the 2-vector ``half_sq`` when
    the GNS half-gradients are available). ``params`` is the pre-update
    tree, ``new_params`` post-update; the new-param norm doubles as the
    cross-replica digest (bitwise-deterministic per step on identical
    replicas)."""
    loss32 = jnp.asarray(loss, jnp.float32)
    old_sq = _sq_norm(params)
    delta = jax.tree_util.tree_map(
        lambda new, old: new.astype(jnp.float32) - old.astype(jnp.float32)
        if jnp.issubdtype(new.dtype, jnp.inexact)
        else jnp.zeros((), jnp.float32),
        new_params,
        params,
    )
    bundle = {
        "loss": loss32,
        "grad_norm": jnp.sqrt(_sq_norm(grads)),
        "param_norm": jnp.sqrt(_sq_norm(new_params)),
        "update_ratio": jnp.sqrt(_sq_norm(delta))
        / jnp.maximum(jnp.sqrt(old_sq), 1e-12),
        "nonfinite": _nonfinite_count(grads)
        + (~jnp.isfinite(loss32)).astype(jnp.float32),
    }
    if halves is not None:
        g1, g2 = halves
        bundle["half_sq"] = jnp.stack([_sq_norm(g1), _sq_norm(g2)])
        bundle["batch"] = jnp.asarray(0 if batch is None else batch, jnp.float32)
    return bundle


def gns_estimates(big_sq: float, small_sq: float, batch: float) -> Tuple[float, float]:
    """One-step unbiased estimators from McCandlish et al. appendix A:
    given ``|G_big|^2`` at batch ``B`` and the mean half-batch
    ``|G_small|^2`` at ``B/2``, return ``(|G|^2 estimate, tr(Sigma)
    estimate)``; the noise scale is ``mean(s) / mean(g2)`` over a
    window of these pairs (each pair alone is far too noisy)."""
    # g2 = (B_big*big - B_small*small) / (B_big - B_small), B_small = B/2
    g2 = 2.0 * big_sq - small_sq
    # s = (small - big) / (1/B_small - 1/B_big) = B * (small - big)
    s = batch * (small_sq - big_sq)
    return g2, s


# -- fingerprints (the resize continuity sentinel) ------------------------


def host_param_norm(state) -> float:
    """Host recompute of the global param L2 norm (f64 accumulation) —
    the save-time and restore-time sides of the fingerprint run the
    exact same math, so equality is bitwise up to float64 summation."""
    tree = getattr(state, "params", state)
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            continue
        arr = np.abs(np.asarray(jax.device_get(leaf))).astype(np.float64)
        total += float(np.sum(np.square(arr)))
    return math.sqrt(total)


def latest_loss() -> Optional[float]:
    """The newest loss any probe in this process has buffered (one
    device_get of a 0-d scalar; None when no probe has run or the
    value is non-finite — ``json`` cannot carry Infinity portably and
    a non-finite stamp could never gate continuity anyway)."""
    with _LATEST_LOCK:
        latest = _LATEST
    if latest is None:
        return None
    try:
        loss = float(jax.device_get(latest[1]["loss"]))
    except (RuntimeError, KeyError):
        # RuntimeError is jax's "Array has been deleted" — the buffered
        # bundle's loss was donated into a later step before this read.
        # That exact state (not arbitrary breakage) reads as no loss.
        return None
    return loss if math.isfinite(loss) else None


def fingerprint_for_save(state, step: int) -> Dict[str, Any]:
    return {
        "step": int(step),
        "param_norm": host_param_norm(state),
        "loss": latest_loss(),
    }


def stamp_fingerprint(status_doc: Dict, state, step: int) -> Dict:
    """Return a copy of the checkpoint status document carrying the
    numerics fingerprint under ``meta.numerics`` (no-op when the plane
    is disabled)."""
    if not enabled():
        return status_doc
    doc = dict(status_doc)
    meta = dict(doc.get("meta") or {})
    meta["numerics"] = fingerprint_for_save(state, step)
    doc["meta"] = meta
    return doc


def verify_fingerprint(state, fingerprint, tol: Optional[float] = None) -> Tuple[bool, str]:
    """Re-derive the restored state's param norm and compare against the
    stamped one. A mismatch means the bytes Orbax handed back are not
    the bytes the trainer saved — the caller treats the candidate like
    any other corrupt checkpoint (fallback + quarantine)."""
    if not fingerprint or not enabled():
        return True, "no fingerprint"
    want = fingerprint.get("param_norm") if isinstance(fingerprint, dict) else None
    if want is None:
        return True, "fingerprint has no param_norm"
    if tol is None:
        tol = float(os.environ.get("EDL_NUMERICS_FP_TOL", DEFAULT_FP_TOL))
    have = host_param_norm(state)
    if not math.isfinite(have):
        return False, "restored param norm is non-finite (%r)" % have
    rel = abs(have - float(want)) / max(abs(float(want)), 1e-12)
    if rel > tol:
        return False, (
            "param norm %.9g vs stamped %.9g at step %s (rel %.3g > %.3g)"
            % (have, float(want), fingerprint.get("step"), rel, tol)
        )
    return True, "param norm match (rel %.3g)" % rel


# -- host side ------------------------------------------------------------


class NumericsProbe:
    """Host half of the plane: throttled device->host transfer, metric
    export, GNS/digest/spike derivation, and the resume-continuity
    check. One instance per training process; not thread-safe beyond
    the module-level latest-bundle buffer (the train loop is the only
    caller)."""

    def __init__(
        self,
        every: Optional[int] = None,
        rank: int = 0,
        client=None,
        job_id: str = "",
    ) -> None:
        if every is None:
            every = int(os.environ.get("EDL_NUMERICS_EVERY", DEFAULT_EVERY))
        self.every = max(1, int(every))
        self.rank = int(rank)
        self._client = client
        self._job = job_id
        self._loss_tol = float(os.environ.get("EDL_NUMERICS_LOSS_TOL", DEFAULT_LOSS_TOL))
        self._calls = 0
        self._held: Optional[Tuple[int, Dict[str, Any]]] = None
        self._last_pub_step: Optional[int] = None
        self._gns_win: collections.deque = collections.deque(maxlen=_GNS_WINDOW)
        self._loss_hist: collections.deque = collections.deque(maxlen=_SPIKE_HISTORY)
        self._expected: Optional[Dict] = None
        self._gauges: Dict[str, obs_metrics.Gauge] = {}
        self._nonfinite: Optional[obs_metrics.Counter] = None
        self._closed = False
        self.published = 0  # publishes performed (tests assert throttling)

    # -- step ingestion ---------------------------------------------------

    def on_step(self, step: int, bundle: Optional[Dict[str, Any]]) -> None:
        """Buffer this step's device bundle; publish on the throttle
        cadence. Publishing fetches the *previous* buffered bundle —
        already retired by a full step of device work — except on the
        very first call, which publishes synchronously so the plane is
        armed with real data the moment training produces any (a
        registered-but-never-set gauge would render 0.0 and trip the
        grad-stall rule during a long first-step compile)."""
        if self._closed or bundle is None:
            return
        self._calls += 1
        prev = self._held
        self._held = (int(step), bundle)
        global _LATEST
        with _LATEST_LOCK:
            _LATEST = self._held
        if self._calls == 1:
            self._publish(int(step), bundle)
        elif self._calls % self.every == 0 and prev is not None:
            self._publish(prev[0], prev[1])

    def close(self) -> None:
        """Flush the held bundle (the final step's numbers must not be
        lost to the throttle) and stop accepting steps."""
        if self._closed:
            return
        self._closed = True
        if self._held is not None:
            self._publish(self._held[0], self._held[1])

    def expect(self, fingerprint: Optional[Dict]) -> None:
        """Arm the post-resume continuity check: at the next publish the
        observed loss is compared against the checkpoint's stamped loss
        and the verdict is flight-recorded as ``numerics_resume`` (the
        ``numerics_continuous`` chaos invariant reads these). A None /
        loss-less fingerprint arms nothing."""
        if isinstance(fingerprint, dict):
            self._expected = fingerprint

    # -- publication ------------------------------------------------------

    def _gauge(self, name: str, help_text: str) -> obs_metrics.Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = obs_metrics.gauge(name, help_text)
            self._gauges[name] = g
        return g

    def _publish(self, step: int, bundle: Dict[str, Any]) -> None:
        if step == self._last_pub_step:
            return
        self._last_pub_step = step
        try:
            vals = jax.device_get(bundle)
        except Exception as exc:  # noqa: BLE001 — a deleted buffer must not kill the loop
            logger.warning("numerics fetch failed at step %d: %s", step, exc)
            return
        self.published += 1
        loss = float(vals["loss"])
        grad_norm = float(vals["grad_norm"])
        param_norm = float(vals["param_norm"])
        update_ratio = float(vals["update_ratio"])
        nonfinite = int(vals["nonfinite"])

        self._gauge("edl_train_loss", "training loss, last published step").set(loss)
        self._gauge(
            "edl_train_grad_norm", "global gradient L2 norm, last published step"
        ).set(grad_norm)
        self._gauge(
            "edl_train_param_norm",
            "global parameter L2 norm (the cross-replica digest)",
        ).set(param_norm)
        self._gauge(
            "edl_train_update_ratio",
            "|param update| / |params|, last published step",
        ).set(update_ratio)
        if self._nonfinite is None:
            # the counter registers with the gauges (renders 0 from the
            # first publish) so the nan-detected rate rule sees the
            # 0 -> N jump instead of a series born already at N
            self._nonfinite = obs_metrics.counter(
                "edl_train_nonfinite_total",
                "non-finite elements seen in gradients/loss",
            )
        if nonfinite > 0:
            self._nonfinite.inc(nonfinite)
            obs_events.record(
                "nonfinite", fsync=True, step=step, count=nonfinite, loss=loss
            )

        gns = self._update_gns(vals)
        divergence = self._update_divergence(step, param_norm)
        self._check_spike(step, loss)
        self._resolve_expected(step, loss)
        obs_events.record(
            "numerics",
            step=step,
            loss=loss,
            grad_norm=grad_norm,
            param_norm=param_norm,
            update_ratio=update_ratio,
            nonfinite=nonfinite,
            gns=gns,
            divergence=divergence,
        )

    def _update_gns(self, vals) -> Optional[float]:
        half_sq = vals.get("half_sq")
        if half_sq is None:
            return None
        batch = float(vals.get("batch", 0.0))
        big_sq = float(vals["grad_norm"]) ** 2
        small_sq = float(np.mean(np.asarray(half_sq, dtype=np.float64)))
        if batch < 2 or not (math.isfinite(big_sq) and math.isfinite(small_sq)):
            return None
        self._gns_win.append(gns_estimates(big_sq, small_sq, batch))
        mean_g2 = sum(p[0] for p in self._gns_win) / len(self._gns_win)
        mean_s = sum(p[1] for p in self._gns_win) / len(self._gns_win)
        if mean_g2 <= 1e-12:
            return None  # all signal is noise: no stable estimate yet
        gns = mean_s / mean_g2
        self._gauge(
            "edl_train_grad_noise_scale",
            "windowed gradient-noise-scale estimate (McCandlish et al.)",
        ).set(gns)
        return gns

    def _update_divergence(self, step: int, param_norm: float) -> Optional[float]:
        """Publish this replica's digest and read the spread across dp
        replicas *at the same step* (digests from different steps are
        incomparable: params move every step). Best-effort: a dead
        store reads as no divergence signal, never as a stall."""
        if self._client is None or not self._job:
            return None
        prefix = "/%s/%s/digest/" % (self._job, _DIGEST_SERVICE)
        try:
            self._client.put(
                prefix + str(self.rank),
                json.dumps({"step": step, "digest": param_norm}).encode(),
            )
            rows, _rev = self._client.range(prefix)
        except Exception as exc:  # noqa: BLE001
            logger.warning("digest exchange failed: %s", exc)
            return None
        digests = []
        for _key, value, _c, _m in rows:
            try:
                doc = json.loads(value)
            except ValueError:
                continue
            if doc.get("step") == step:
                digests.append(float(doc.get("digest", 0.0)))
        if len(digests) < 2:
            return None  # peers not at this step yet: nothing comparable
        spread = (max(digests) - min(digests)) / max(abs(max(digests)), 1e-12)
        self._gauge(
            "edl_train_replica_divergence",
            "relative spread of the param digest across dp replicas",
        ).set(spread)
        return spread

    def _check_spike(self, step: int, loss: float) -> None:
        """Host-side twin of the ``loss-spike`` monitor rule, so the
        flight recorder carries the instant even when no monitor is
        scraping this process (edl-timeline overlays these)."""
        hist = [v for v in self._loss_hist if math.isfinite(v)]
        if math.isfinite(loss):
            self._loss_hist.append(loss)
        if len(hist) < _SPIKE_MIN_HISTORY:
            return
        mean = sum(hist) / len(hist)
        var = sum((v - mean) ** 2 for v in hist) / len(hist)
        std = max(math.sqrt(var), 0.05 * abs(mean), 1e-12)
        z = (loss - mean) / std if math.isfinite(loss) else float("inf")
        if z > _SPIKE_Z:
            obs_events.record(
                "loss_spike", fsync=True, step=step, loss=loss,
                z=(z if math.isfinite(z) else 1e30), mean=mean,
            )

    def _resolve_expected(self, step: int, loss: float) -> None:
        if self._expected is None:
            return
        fp = self._expected
        self._expected = None
        want = fp.get("loss")
        if want is None:
            ok = math.isfinite(loss)
            rel = None
            detail = "no stamped loss; observed %s" % ("finite" if ok else "non-finite")
        elif not math.isfinite(loss):
            ok, rel = False, None
            detail = "post-resume loss is non-finite"
        else:
            rel = (loss - float(want)) / max(abs(float(want)), 1e-9)
            ok = rel <= self._loss_tol
            detail = "rel %.3g vs tol %.3g" % (rel, self._loss_tol)
        obs_events.record(
            "numerics_resume",
            fsync=True,
            step=step,
            ok=ok,
            expected_loss=want,
            actual_loss=loss if math.isfinite(loss) else None,
            rel=rel,
            ref_step=fp.get("step"),
            detail=detail,
        )
        if not ok:
            logger.warning(
                "resume continuity FAILED at step %d: %s (ckpt step %s)",
                step, detail, fp.get("step"),
            )
