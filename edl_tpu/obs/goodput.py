"""Goodput ledger: classify every second of wall-clock, per process.

Pollux (OSDI '21) made *goodput* — useful training throughput after all
overheads — the metric elastic schedulers optimize. This module is the
accounting half of that idea for edl_tpu: a tiny per-process state
machine that attributes every second of a worker's life to exactly one
of

    train         dispatching/executing training steps (the product)
    compile       first-step jit trace + XLA compile (or cache load)
    data_wait     blocked on the input pipeline / distill teachers
    ckpt_save     blocked in a checkpoint save (incl. emergency saves)
    ckpt_restore  blocked in a checkpoint restore
    restage       elastic transition: spawn/init/jax.distributed re-init
    drain         honoring a preemption notice (emergency-ckpt window)
    stalled       known-wedged (watchdog verdict, injected wedge)
    down          process not running at all (derived by the merger —
                  a dead process cannot record its own absence)

Transitions are cheap (a lock + counter bump) and are fsync'd into the
flight recorder (:mod:`edl_tpu.obs.events`), so the attribution survives
SIGKILL. Exported metrics:

- ``edl_goodput_seconds_total{state,cause}`` — closed-interval seconds;
- ``edl_goodput_ratio`` — train seconds / all accounted seconds,
  including the currently open interval (sampled at scrape time).

:func:`process_intervals` / :func:`attribute` turn merged flight events
back into per-process state intervals and a job-level attribution table
that partitions wall-clock exactly (``tools/edl_timeline.py`` prints it;
``chaos.invariants.goodput_accounted`` conformance-tests it).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics

STATES = (
    "train",
    "compile",
    "data_wait",
    "ckpt_save",
    "ckpt_restore",
    "restage",
    "drain",
    "stalled",
    "aot_compile",
    "down",
)

# when several processes are in different states over the same second,
# the JOB lane takes the first match here: training anywhere means the
# job made progress that second; "down" never wins while anyone is alive.
# aot_compile (the resize ladder's speculative background compiles,
# train/aot.py) ranks below every foreground state: it runs on a spare
# thread beside live training and must never displace the train lane.
PRIORITY = (
    "train",
    "compile",
    "ckpt_restore",
    "ckpt_save",
    "data_wait",
    "restage",
    "drain",
    "stalled",
    "aot_compile",
    "down",
)

TRANSITION_EVENT = "goodput"

# the steady-state train<->data_wait flap happens twice per step (and a
# standalone DistillReader opens/closes data_wait per batch): those are
# appended (an O_APPEND write survives the process dying — only a HOST
# death can lose the un-synced tail) but not fsync'd; every rarer
# transition (drain, restage, ckpt_*, stalled, compile) is fsync'd so the
# postmortem-critical records survive even machine death.
_CHATTY = ("train", "data_wait")


def _rare(state, prev) -> bool:
    return not (
        (state is None or state in _CHATTY)
        and (prev is None or prev in _CHATTY)
    )


class GoodputLedger:
    """Per-process wall-clock attribution state machine.

    One open state at a time; :meth:`enter` closes the previous interval
    into ``edl_goodput_seconds_total{state,cause}`` and fsync's the
    transition into the flight recorder. :meth:`phase` is the nesting
    form (a checkpoint save inside a drain returns to ``drain``).

    ``component`` stamps this ledger's flight records with a lane of its
    own (the merger keys lanes by ``(component, pid)``): a SECOND ledger
    in the same process — the AOT ladder thread beside the training
    loop — can then account for itself without corrupting the process
    singleton's interval chain.
    """

    def __init__(
        self,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        component: Optional[str] = None,
    ) -> None:
        self._component = component
        reg = registry if registry is not None else obs_metrics.default_registry()
        self._m_seconds = reg.counter(
            "edl_goodput_seconds_total",
            "wall-clock seconds attributed per goodput state, by cause",
        )
        self._m_ratio = reg.gauge(
            "edl_goodput_ratio",
            "train seconds / all accounted seconds (incl. the open state)",
        )
        if component is None:
            # only the process singleton drives the exported ratio: a
            # component-lane ledger (the AOT ladder's) re-pointing the
            # shared gauge's render callback would replace the worker's
            # goodput% with its own (train-less, ~0) ratio
            self._m_ratio.set_fn(self._ratio)
        self._lock = threading.Lock()
        self._state: Optional[str] = None
        self._cause = ""
        self._since: Optional[float] = None  # monotonic
        self._accounted: Dict[str, float] = {}

    # -- recording ---------------------------------------------------------

    def enter(self, state: str, cause: str = "") -> Optional[str]:
        """Transition to ``state``; returns the previous state. The
        closed interval's seconds land in the counter under the PREVIOUS
        state's labels; the transition record carries both ends."""
        if state not in STATES:
            raise ValueError(
                "unknown goodput state %r (have: %s)" % (state, ", ".join(STATES))
            )
        now = time.monotonic()
        with self._lock:
            prev, prev_cause = self._state, self._cause
            dur = 0.0
            if prev is not None and self._since is not None:
                dur = max(0.0, now - self._since)
                self._m_seconds.inc(dur, state=prev, cause=prev_cause)
                self._accounted[prev] = self._accounted.get(prev, 0.0) + dur
            self._state, self._cause, self._since = state, cause, now
        obs_events.record(
            TRANSITION_EVENT,
            fsync=_rare(state, prev),
            state=state,
            cause=cause,
            prev=prev,
            dur=round(dur, 6),
            **({"component": self._component} if self._component else {}),
        )
        return prev

    def phase(self, state: str, cause: str = "") -> "_Phase":
        """``with ledger.phase("ckpt_save"): ...`` — enters ``state`` and
        restores the previous state (and cause) on exit."""
        return _Phase(self, state, cause)

    def close(self, cause: str = "") -> None:
        """Finalize: close the open interval without opening another
        (clean exits; a killed process just leaves its interval open and
        the merger bounds it by the process's last record)."""
        now = time.monotonic()
        with self._lock:
            prev, prev_cause = self._state, self._cause
            dur = 0.0
            if prev is not None and self._since is not None:
                dur = max(0.0, now - self._since)
                self._m_seconds.inc(dur, state=prev, cause=prev_cause)
                self._accounted[prev] = self._accounted.get(prev, 0.0) + dur
            self._state, self._cause, self._since = None, "", None
        if prev is not None:
            obs_events.record(
                TRANSITION_EVENT,
                fsync=_rare(None, prev),
                state=None,
                cause=cause,
                prev=prev,
                dur=round(dur, 6),
                **({"component": self._component} if self._component else {}),
            )

    # -- reading -----------------------------------------------------------

    def state(self) -> Optional[str]:
        with self._lock:
            return self._state

    def seconds(self, state: Optional[str] = None) -> float:
        """Accounted seconds for ``state`` (or all), open interval
        included."""
        now = time.monotonic()
        with self._lock:
            acc = dict(self._accounted)
            if self._state is not None and self._since is not None:
                acc[self._state] = acc.get(self._state, 0.0) + (now - self._since)
        if state is not None:
            return acc.get(state, 0.0)
        return sum(acc.values())

    def _ratio(self) -> float:
        total = self.seconds()
        if total <= 0:
            return 0.0
        return self.seconds("train") / total


class _Phase:
    __slots__ = ("_ledger", "_state", "_cause", "_prev", "_prev_cause")

    def __init__(self, ledger: GoodputLedger, state: str, cause: str) -> None:
        self._ledger = ledger
        self._state = state
        self._cause = cause

    def __enter__(self) -> "_Phase":
        with self._ledger._lock:
            self._prev = self._ledger._state
            self._prev_cause = self._ledger._cause
        self._ledger.enter(self._state, self._cause)
        return self

    def __exit__(self, *exc) -> None:
        if self._prev is not None:
            self._ledger.enter(self._prev, self._prev_cause)
        else:
            self._ledger.close(cause=self._cause)


_ledger: Optional[GoodputLedger] = None
_ledger_lock = threading.Lock()


def ledger() -> GoodputLedger:
    """The process goodput ledger (lazy singleton)."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = GoodputLedger()
        return _ledger


def enter(state: str, cause: str = "") -> Optional[str]:
    return ledger().enter(state, cause)


def phase(state: str, cause: str = "") -> _Phase:
    return ledger().phase(state, cause)


def close(cause: str = "") -> None:
    global _ledger
    with _ledger_lock:
        led = _ledger
    if led is not None:
        led.close(cause=cause)


# -- merged-run attribution ---------------------------------------------------

Lane = Tuple[str, int]  # (component, pid)


def process_intervals(
    events: Iterable[Dict],
) -> Dict[Lane, List[Tuple[float, float, str]]]:
    """Rebuild per-process ``(t0, t1, state)`` intervals from merged
    flight events.

    Each ``goodput`` transition closes the previous state exactly at its
    own timestamp, so a process's intervals are contiguous from its
    first transition to its last one; the OPEN interval of a process
    that never closed (killed) is bounded by that process's last flight
    record of ANY kind — a killed worker accounts for itself up to its
    final write, and the gap until its successor is genuine ``down``
    time."""
    per_proc: Dict[Lane, List[Dict]] = {}
    last_seen: Dict[Lane, float] = {}
    for ev in events:
        lane = (str(ev.get("component", "proc")), int(ev.get("pid", 0)))
        ts = float(ev.get("ts", 0.0))
        last_seen[lane] = max(last_seen.get(lane, ts), ts)
        if ev.get("event") == TRANSITION_EVENT:
            per_proc.setdefault(lane, []).append(ev)
    out: Dict[Lane, List[Tuple[float, float, str]]] = {}
    for lane, transitions in per_proc.items():
        transitions.sort(key=lambda e: float(e.get("ts", 0.0)))
        intervals: List[Tuple[float, float, str]] = []
        for ev in transitions:
            ts = float(ev.get("ts", 0.0))
            prev = ev.get("prev")
            dur = float(ev.get("dur", 0.0) or 0.0)
            if prev and dur > 0:
                intervals.append((ts - dur, ts, str(prev)))
        tail = transitions[-1]
        open_state = tail.get("state")
        if open_state:  # never closed: bound by the last record we have
            t0 = float(tail.get("ts", 0.0))
            t1 = last_seen[lane]
            if t1 > t0:
                intervals.append((t0, t1, str(open_state)))
        if intervals:
            out[lane] = sorted(intervals)
    return out


def attribute(
    events: Iterable[Dict],
    t0: Optional[float] = None,
    t1: Optional[float] = None,
) -> Dict:
    """Job-level wall-clock attribution over merged flight events.

    Sweeps the union of every process's state intervals across
    ``[t0, t1]`` (default: the events' own span): each elementary slice
    is attributed to the highest-:data:`PRIORITY` state active in ANY
    process, or ``down`` when no process covers it. The result
    PARTITIONS the window — percentages sum to 100 by construction.

    Returns ``{"wall_s", "t0", "t1", "states": {state: seconds},
    "lanes": {"component-pid": {state: seconds}},
    "covered_s": seconds where >=1 process accounted for itself}``.
    """
    events = list(events)
    intervals = process_intervals(events)
    all_ts = [float(e.get("ts", 0.0)) for e in events]
    if t0 is None:
        t0 = min(all_ts) if all_ts else 0.0
    if t1 is None:
        t1 = max(all_ts) if all_ts else 0.0
    wall = max(0.0, t1 - t0)
    states: Dict[str, float] = {}
    covered = 0.0
    if wall > 0:
        bounds = {t0, t1}
        for spans in intervals.values():
            for a, b, _s in spans:
                if a < t1 and b > t0:
                    bounds.add(min(max(a, t0), t1))
                    bounds.add(min(max(b, t0), t1))
        edges = sorted(bounds)
        for a, b in zip(edges, edges[1:]):
            if b <= a:
                continue
            mid = (a + b) / 2.0
            active = {
                s
                for spans in intervals.values()
                for (x, y, s) in spans
                if x <= mid < y
            }
            if active:
                covered += b - a
                pick = next((s for s in PRIORITY if s in active), "down")
            else:
                pick = "down"
            states[pick] = states.get(pick, 0.0) + (b - a)
    lanes = {
        "%s-%d" % lane: _lane_totals(spans, t0, t1)
        for lane, spans in sorted(intervals.items())
    }
    return {
        "wall_s": wall,
        "t0": t0,
        "t1": t1,
        "states": states,
        "lanes": lanes,
        "covered_s": covered,
    }


def job_goodput(
    events: Iterable[Dict],
    t0: Optional[float] = None,
    t1: Optional[float] = None,
) -> Dict:
    """The one job-level goodput merge every consumer shares.

    ``edl-timeline``'s attribution view, the run archive's rollup
    scalars and the scale plane's objective all used to re-derive the
    same numbers from :func:`attribute` independently; this helper is
    the single source of truth. Returns::

        {"attribution": <attribute() dict>,
         "wall_s": float,
         "ratio": float,              # train seconds / wall seconds
         "rollup": {"wall_s", "goodput_ratio", "<state>_s", ...}}

    ``rollup`` keys and rounding match the historical archive rollup
    shape exactly — archived runs stay comparable across PRs.
    """
    att = attribute(events, t0=t0, t1=t1)
    wall = att["wall_s"]
    states = att["states"]
    ratio = states.get("train", 0.0) / wall if wall > 0 else 0.0
    rollup: Dict[str, float] = {"wall_s": round(wall, 3)}
    if wall > 0:
        rollup["goodput_ratio"] = round(ratio, 4)
    for state in (
        "restage", "drain", "down", "compile", "data_wait",
        "ckpt_restore", "ckpt_save", "stalled",
    ):
        if states.get(state):
            rollup["%s_s" % state] = round(states[state], 3)
    return {
        "attribution": att,
        "wall_s": wall,
        "ratio": ratio,
        "rollup": rollup,
    }


def _lane_totals(
    spans: List[Tuple[float, float, str]], t0: float, t1: float
) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for a, b, s in spans:
        a, b = max(a, t0), min(b, t1)
        if b > a:
            out[s] = out.get(s, 0.0) + (b - a)
    return out


def render_table(attribution: Dict) -> str:
    """The attribution dict as an aligned text table whose percent
    column sums to 100.0 (the acceptance artifact of edl-timeline)."""
    wall = attribution.get("wall_s", 0.0)
    states = attribution.get("states", {})
    lines = ["%-14s %12s %8s" % ("state", "seconds", "%")]
    total_s = 0.0
    total_pct = 0.0
    for state in PRIORITY:
        if state not in states:
            continue
        sec = states[state]
        pct = 100.0 * sec / wall if wall > 0 else 0.0
        total_s += sec
        total_pct += pct
        lines.append("%-14s %12.3f %8.2f" % (state, sec, pct))
    lines.append("%-14s %12.3f %8.2f" % ("total", total_s, total_pct))
    return "\n".join(lines)
