"""Flight recorder: crash-safe, append-only structured wide events.

The metrics plane answers "how much"; the span tracer answers "how long"
— but both live in process memory until an export tick, so the most
interesting process of any elastic run (the one that just took a SIGKILL
or a spot reclaim) leaves its last seconds behind only by luck. The
flight recorder is the black box: every record is ONE ``os.write`` of
one JSON line to an ``O_APPEND`` segment file under ``EDL_FLIGHT_DIR``,
optionally ``fsync``'d (state transitions are; chatty step markers are
not), so a process killed with ``SIGKILL`` mid-step still leaves every
transition it ever recorded on disk, readable by
``tools/edl_timeline.py`` and the chaos ``goodput_accounted`` invariant.

Layout: ``{EDL_FLIGHT_DIR}/{component}-{pid}.{seq:04d}.flight.jsonl``,
one file series per process. Segments rotate at ``EDL_FLIGHT_SEG_BYTES``
(default 4 MiB) and at most ``EDL_FLIGHT_SEGS`` (default 8) are kept per
process — a million-step job costs a bounded few tens of MB, never a
full disk. A torn final line (the write the kill interrupted) is skipped
by the reader; every complete line before it survives.

Env contract:

    EDL_FLIGHT_DIR        directory for segments; unset disables the
                          recorder entirely (``record()`` is a cached
                          None-check — production hot paths pay one
                          attribute load, like the chaos plane).
    EDL_FLIGHT_SEG_BYTES  rotate threshold per segment (default 4 MiB).
    EDL_FLIGHT_SEGS       segments kept per process (default 8).
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Dict, List, Optional

from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.events")

ENV_DIR = "EDL_FLIGHT_DIR"
DEFAULT_SEG_BYTES = 4 << 20
DEFAULT_SEGS = 8
_SUFFIX = ".flight.jsonl"


class FlightRecorder:
    """Append-only JSONL event log for ONE process.

    Thread-safe; every :meth:`record` is a single append ``write`` (plus
    an ``fsync`` when asked), so no record can be half-lost to an
    in-process buffer when the process dies — the only casualty of a
    SIGKILL is the one line it interrupted, which the reader skips.

    ``suffix`` lets other subsystems reuse the crash-safe ring-segment
    design under their own file extension (the monitor plane retains its
    scraped time series as ``*.series.jsonl`` this way) without their
    records being swept up by flight-segment readers. ``stable_path``
    goes one step further: the recorder writes to ONE named file and
    never rotates — the run archive's ``runs/index.jsonl`` is an
    append-forever history, so it reuses the write discipline (one
    ``O_APPEND`` write per record, fsync'd, error-contained) without the
    per-process ring naming.
    """

    def __init__(
        self,
        directory: str,
        component: str = "proc",
        pid: Optional[int] = None,
        seg_bytes: Optional[int] = None,
        max_segs: Optional[int] = None,
        suffix: str = _SUFFIX,
        stable_path: Optional[str] = None,
    ) -> None:
        self.directory = directory
        self.component = component
        self.suffix = suffix
        self.pid = os.getpid() if pid is None else pid
        if seg_bytes is None:
            seg_bytes = int(
                os.environ.get("EDL_FLIGHT_SEG_BYTES", DEFAULT_SEG_BYTES)
            )
        if max_segs is None:
            max_segs = int(os.environ.get("EDL_FLIGHT_SEGS", DEFAULT_SEGS))
        self._seg_bytes = max(4096, seg_bytes)
        self._max_segs = max(1, max_segs)
        self._stable_path = stable_path
        if stable_path is not None:
            # a stable-path recorder never rotates: the rotate threshold
            # is pushed out of reach so the ring logic stays inert
            self._seg_bytes = 1 << 62
        self._lock = threading.Lock()
        self._seq = 0
        self._fd: Optional[int] = None
        self._written = 0

    def _seg_path(self, seq: int) -> str:
        if self._stable_path is not None:
            return self._stable_path
        return os.path.join(
            self.directory,
            "%s-%d.%04d%s" % (self.component, self.pid, seq, self.suffix),
        )

    def _open_segment(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = self._seg_path(self._seq)
        heal = False
        if self._stable_path is not None:
            # a SHARED stable file outlives its writers: a previous
            # writer killed mid-line leaves a torn tail with no newline,
            # and a plain append would concatenate THIS writer's first
            # record onto it — two records lost instead of one. Terminate
            # the torn tail first; the reader skips the bad line.
            try:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    heal = f.read(1) != b"\n"
            except (OSError, ValueError):
                heal = False  # absent or empty file needs no healing
        self._fd = os.open(
            path,
            os.O_WRONLY | os.O_CREAT | os.O_APPEND,
            0o644,
        )
        if heal:
            os.write(self._fd, b"\n")
        self._written = 0

    def _rotate_locked(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
        self._seq += 1
        # ring semantics: drop the oldest segment beyond the keep budget
        drop = self._seq - self._max_segs
        if drop >= 0:
            try:
                os.unlink(self._seg_path(drop))
            except OSError:
                pass
        self._open_segment()

    def record(self, event: str, fsync: bool = False, **fields) -> None:
        """Append one wide event; ``fsync=True`` for state transitions
        (the records postmortems cannot afford to lose)."""
        doc: Dict = {
            "ts": time.time(),
            "event": event,
            "component": self.component,
            "pid": self.pid,
        }
        if fields:
            doc.update(fields)
        # distributed tracing: black-box records carry the active trace
        # id, so flights, spans, and goodput lanes of one operation
        # (restage, drain) share one key edl-timeline can join on.
        # Disarmed cost: one attribute load (fault-point discipline).
        if obs_trace.PROPAGATION.armed and "trace_id" not in doc:
            tid = obs_trace.current_trace_id()
            if tid is not None:
                doc["trace_id"] = tid
        try:
            line = (json.dumps(doc, default=str) + "\n").encode()
        except (TypeError, ValueError):
            return  # one unserializable field must not break the recorder
        with self._lock:
            try:
                if self._fd is None:
                    self._open_segment()
                elif self._written >= self._seg_bytes:
                    self._rotate_locked()
                os.write(self._fd, line)
                self._written += len(line)
                if fsync:
                    os.fsync(self._fd)
            except OSError as exc:
                # a full/unwritable disk must not take down the workload;
                # drop the fd so a later record can retry a fresh open
                logger.warning("flight record dropped: %s", exc)
                if self._fd is not None:
                    try:
                        os.close(self._fd)
                    except OSError:
                        pass
                    self._fd = None

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


# -- per-process singleton ----------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_checked = False
_lock = threading.Lock()


def get_recorder(component: Optional[str] = None) -> Optional[FlightRecorder]:
    """The process flight recorder, or None when ``EDL_FLIGHT_DIR`` is
    unset. The first caller names the process (same contract as
    :func:`edl_tpu.obs.trace.get_tracer`)."""
    global _recorder, _checked
    with _lock:
        if _recorder is None and not _checked:
            directory = os.environ.get(ENV_DIR, "").strip()
            # cache-warming shadow stages inherit the job env but must
            # not pollute the job's black box (same rule as the obs
            # keyspace in train/context._mount_obs)
            if os.environ.get("EDL_WARM_ONLY") == "1":
                directory = ""
            if directory:
                from edl_tpu.obs.trace import _default_component

                _recorder = FlightRecorder(
                    directory, component=component or _default_component()
                )
            _checked = True
        elif (
            _recorder is not None
            and component
            and _recorder.component == "proc"
        ):
            _recorder.component = component
        return _recorder


def record(event: str, fsync: bool = False, **fields) -> None:
    """Record into the process flight recorder; no-op when disabled."""
    rec = _recorder if _checked else get_recorder()
    if rec is not None:
        rec.record(event, fsync=fsync, **fields)


def reset() -> None:
    """Forget the singleton so the env is re-read (tests)."""
    global _recorder, _checked
    with _lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None
        _checked = False


# -- reading back -------------------------------------------------------------


def _parse_lines(data: bytes, require_ts: bool = True) -> List[Dict]:
    """The torn-tail parse discipline shared by every JSONL reader of
    this module: blank, unparseable (torn tail) and non-dict lines are
    skipped, never fatal."""
    docs: List[Dict] = []
    for raw in data.split(b"\n"):
        if not raw.strip():
            continue
        try:
            doc = json.loads(raw)
        except ValueError:
            continue  # torn tail line
        if isinstance(doc, dict) and (not require_ts or "ts" in doc):
            docs.append(doc)
    return docs


def read_segments(directory: str, suffix: str = _SUFFIX) -> List[Dict]:
    """Parse every flight segment under ``directory`` into one
    ts-ordered event list. Torn lines (the write a kill interrupted) and
    unparseable lines are skipped — a dead process's segments must never
    hide a live process's records."""
    events: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "*" + suffix))):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        events.extend(_parse_lines(data))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events


def read_records(path: str) -> List[Dict]:
    """Parse ONE append-only JSONL file with the torn-tail discipline,
    keeping file order (the run-archive index is append-ordered history,
    not a ts-sorted merge)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    return _parse_lines(data, require_ts=False)
