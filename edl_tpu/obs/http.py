"""/metrics + /healthz HTTP endpoints served from a daemon thread.

Every long-lived edl_tpu process (store server, launcher, data
dispatcher, distill teacher, train worker) mounts one
:class:`ObsServer`: ``GET /metrics`` returns the process's default
metrics registry as Prometheus text, ``GET /healthz`` a small JSON
liveness document (component, pid, uptime, plus whatever the owner's
``health_fn`` reports — store revision, queue depths, stage).

Env contract:

    EDL_OBS_PORT    base port. Unset/empty/"off" disables mounting
                    entirely (tests and one-shot tools stay silent);
                    "0" binds an ephemeral port; N tries N, N+1, ...
                    N+15 (several edl processes share a host) and falls
                    back to ephemeral — observability must never lose a
                    port race against the workload it observes.

Processes that belong to a job also *register* their endpoint in the
coordination store under ``/{job}/obs/{component}.{who}`` so
``tools/edl_top.py`` can find every scrape target from the store alone.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from edl_tpu.obs.metrics import MetricsRegistry, default_registry
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.http")

OBS_SERVICE = "obs"
_PORT_SCAN = 16

# wall-clock birth of this process's obs plane (first obs.http import):
# exported from every endpoint as edl_process_start_time_seconds so the
# monitor plane can tell a RESTARTED process (start time jumped) from a
# WEDGED one (start time stable, heartbeats silent).
_PROCESS_START = time.time()


def _register_identity(registry: MetricsRegistry) -> None:
    """Every /metrics endpoint carries the process identity gauges."""
    import sys

    from edl_tpu import __version__

    registry.gauge(
        "edl_process_start_time_seconds",
        "unix time this process's obs plane came up (restart detection)",
    ).set(_PROCESS_START)
    registry.gauge(
        "edl_build_info",
        "constant 1; build identity in labels (version, python)",
    ).set(
        1,
        version=__version__,
        python="%d.%d" % (sys.version_info.major, sys.version_info.minor),
    )


class _Handler(BaseHTTPRequestHandler):
    server_version = "edl-obs/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        owner: "ObsServer" = self.server.obs_owner  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = owner.registry.render().encode()
            self._reply(200, "text/plain; version=0.0.4; charset=utf-8", body)
        elif path == "/healthz":
            body = json.dumps(owner.health()).encode()
            self._reply(200, "application/json", body)
        else:
            self._reply(404, "text/plain", b"not found\n")

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:  # scrapes are not news
        pass


class ObsServer:
    """Daemon-thread HTTP server for one process's observability plane."""

    def __init__(
        self,
        component: str,
        host: str = "0.0.0.0",
        port: int = 0,
        registry: Optional[MetricsRegistry] = None,
        health_fn: Optional[Callable[[], Dict]] = None,
    ) -> None:
        self.component = component
        self.registry = registry if registry is not None else default_registry()
        _register_identity(self.registry)
        self._health_fn = health_fn
        self._t0 = time.monotonic()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs_owner = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        """Routable scrape address (wildcard binds advertise the host IP)."""
        host = self._host
        if host in ("", "0.0.0.0"):
            from edl_tpu.utils.net import get_host_ip

            host = get_host_ip()
        return "%s:%d" % (host, self.port)

    def health(self) -> Dict:
        doc = {
            "status": "ok",
            "component": self.component,
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "time": time.time(),
        }
        if self._health_fn is not None:
            try:
                doc.update(self._health_fn())
            except Exception as exc:  # noqa: BLE001 — health must not 500
                doc["status"] = "degraded"
                doc["health_error"] = str(exc)
        return doc

    def start(self) -> "ObsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.5},
            name="edl-obs-http",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "obs endpoints for %r on :%d (/metrics, /healthz)",
            self.component, self.port,
        )
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)


_servers: Dict[str, ObsServer] = {}
_servers_lock = threading.Lock()


def start_from_env(
    component: str,
    health_fn: Optional[Callable[[], Dict]] = None,
    host: str = "0.0.0.0",
) -> Optional[ObsServer]:
    """Mount the obs plane if ``EDL_OBS_PORT`` opts the process in.

    Idempotent per (process, component): repeated calls return the same
    server. Port contention between co-hosted edl processes resolves by
    scanning ``port..port+15`` then falling back to an ephemeral port.
    """
    spec = os.environ.get("EDL_OBS_PORT", "").strip().lower()
    if spec in ("", "off", "none", "disabled"):
        return None
    with _servers_lock:
        server = _servers.get(component)
        if server is not None:
            if health_fn is not None:
                # an in-process replacement (e.g. a restarted store on
                # the same component) must not serve the dead owner's
                # frozen health — rebind to the newest owner
                server._health_fn = health_fn
            return server
        try:
            base = int(spec)
        except ValueError:
            logger.warning("EDL_OBS_PORT=%r is not a port; obs disabled", spec)
            return None
        if base == 0:
            candidates = [0]
        else:
            # drop out-of-range candidates (a scan reaching past 65535
            # raises OverflowError, not OSError) and always end on an
            # ephemeral fallback — a bad port env var must degrade, not
            # take down the workload it observes
            candidates = [
                p for p in range(base, base + _PORT_SCAN) if 0 < p <= 65535
            ] + [0]
        for port in candidates:
            try:
                server = ObsServer(
                    component, host=host, port=port, health_fn=health_fn
                )
                break
            except (OSError, OverflowError):
                continue
        else:  # pragma: no cover — ephemeral bind failing means no sockets at all
            logger.warning("no bindable obs port for %r; obs disabled", component)
            return None
        _servers[component] = server.start()
        return server


def release_health(component: str, health_fn: Callable[[], Dict]) -> None:
    """Detach a stopped owner's ``health_fn`` from the mounted obs server.

    Identity-guarded (a replacement instance that already rebound is left
    alone). The endpoint then reports ``status: "stale"`` instead of a
    dead component's frozen "ok" — and the closure no longer pins the
    stopped instance (store state, task queues, ...) in memory.
    """
    with _servers_lock:
        server = _servers.get(component)
    if server is not None and server._health_fn is health_fn:
        server._health_fn = _stopped_health


def _stopped_health() -> Dict:
    return {"status": "stale", "detail": "component stopped in this process"}


def stop_all() -> None:
    """Tear down every obs server this process mounted (tests)."""
    with _servers_lock:
        servers = list(_servers.values())
        _servers.clear()
    for server in servers:
        server.stop()


# -- endpoint registration (store-discoverable scrape targets) ---------------


def obs_prefix(job_id: str) -> str:
    return "/%s/%s/" % (job_id, OBS_SERVICE)


def mounted(component: str) -> Optional[ObsServer]:
    """The obs server this process mounted for ``component``, if any."""
    with _servers_lock:
        return _servers.get(component)


def endpoint_payload(endpoint: str) -> bytes:
    return json.dumps(
        {"endpoint": endpoint, "pid": os.getpid(), "ts": time.time()}
    ).encode()


def register_endpoint(client, job_id: str, component: str, who: str, endpoint: str) -> None:
    """Advertise a live /metrics endpoint under the job's obs keyspace.

    Permanent key (edl-top probes liveness itself via /healthz);
    fire-and-forget like all telemetry writers.
    """
    key = "%s%s.%s" % (obs_prefix(job_id), component, who)
    try:
        client.put(key, endpoint_payload(endpoint))
    except Exception as exc:  # noqa: BLE001 — never take down the caller
        logger.warning("obs endpoint %s not registered: %s", key, exc)


def discover_endpoints(client, job_id: str) -> Dict[str, Dict]:
    """Read back ``{component.who: {endpoint, pid, ts}}`` for a job."""
    out: Dict[str, Dict] = {}
    prefix = obs_prefix(job_id)
    try:
        rows, _rev = client.range(prefix)
    except Exception as exc:  # noqa: BLE001
        logger.warning("obs endpoint discovery failed: %s", exc)
        return out
    for key, value, _c, _m in rows:
        try:
            out[key[len(prefix):]] = json.loads(value)
        except ValueError:
            continue
    return out


def parse_metrics_text(text: str) -> Dict[str, Dict[str, float]]:
    """Parse Prometheus exposition text into {name: {labelset: value}}.

    Minimal parser — enough for the series our own ``render`` emits (no
    exemplars, no exotic escapes). Shared by :func:`fetch_metrics` and
    the monitor plane's self-sample (the monitor feeds its own registry
    through the same code path as a scraped endpoint).
    """
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        name, _, labels = series.partition("{")
        try:
            out.setdefault(name, {})["{" + labels if labels else ""] = float(value)
        except ValueError:
            continue
    return out


def fetch_metrics(endpoint: str, timeout: float = 2.0) -> Dict[str, Dict[str, float]]:
    """Scrape ``http://endpoint/metrics`` into {name: {labelset: value}}."""
    import urllib.request

    with urllib.request.urlopen(
        "http://%s/metrics" % endpoint, timeout=timeout
    ) as resp:
        text = resp.read().decode()
    return parse_metrics_text(text)


def fetch_healthz(endpoint: str, timeout: float = 2.0) -> Dict:
    import urllib.request

    with urllib.request.urlopen(
        "http://%s/healthz" % endpoint, timeout=timeout
    ) as resp:
        return json.loads(resp.read().decode())
