"""Profiling plane: live MFU/roofline/HBM telemetry + on-demand capture.

PR 6 closed the loop from measurement to *alert* ("the monitor fired");
this module closes the remaining gap to *explanation* ("here is the
on-device profile of the window that fired"). Three pieces, all worker-
side unless noted:

**Cost model** (pure functions, no jax import). The peak-FLOPs and
HBM-bandwidth tables and the :func:`roofline` estimator used to live in
``bench.py`` — offline, once per benchmark run. They live here now and
``bench.py`` / ``tools/lm_profile.py`` import them back, so the live
plane and the offline bench can never disagree about what a chip can do.

**Live telemetry** (:class:`StepTelemetry`). At stage start the training
loop extracts XLA's own FLOPs / bytes-accessed estimate for one step
(:func:`step_cost` — ``Lowered.cost_analysis()``, a trace without an XLA
compile) and feeds it here; every completed step then updates a sliding
window, exported as

- ``edl_train_step_flops`` / ``edl_train_flops_total`` — the cost model's
  FLOPs for one step, and their cumulative dispatch counter (the
  ``mfu-degraded`` rate rule watches the counter);
- ``edl_train_mfu_ratio`` — windowed model-FLOPs utilization:
  FLOPs/step over the window's *median* step time, against the chip's
  peak (sampled at scrape time, like ``edl_goodput_ratio``; the median
  keeps a checkpoint pause or the compile-heavy first step out of the
  denominator);
- ``edl_train_roofline_mfu_ceiling`` / ``edl_train_arithmetic_intensity``
  — what this program shape *admits* on this chip, so a scraped MFU
  reads as "x of achievable", not "x of a number the memory system
  forbids";
- ``edl_device_hbm_bytes_in_use`` / ``edl_device_hbm_bytes_limit`` —
  from ``device.memory_stats()``, which is absent/None on CPU backends
  and older runtimes: the gauges then simply don't export (guarded, no
  crash).

Unknown device kinds take ``EDL_PEAK_FLOPS`` (override for new chips);
pure-CPU backends fall back to a nominal debug peak so the plumbing is
drivable off-TPU — a CPU "MFU" is a plumbing signal, not a measurement.

**On-demand capture** (:class:`CaptureController`). Workers watch the
job's ``profile/request`` store key; a request (``edl-profile
--request``, or the monitor's auto-capture) makes every worker run one
bounded ``jax.profiler`` trace window — the same window plumbing
``EDL_PROFILE_DIR`` always armed, now store-driven — then publish
``profile/result/{pod}`` with the artifact path and a capture-window
summary (step ms, MFU, HBM). Captures are flight-recorded (fsync'd) so
``edl-timeline`` overlays the profile window on the goodput lanes.

**Alert-triggered snapshots** (:class:`AutoCapture`). The monitor's
``on_fire`` hook: a ``goodput-degraded`` or ``mfu-degraded`` firing
auto-requests one capture, bounded by a per-job cooldown and a
max-captures cap — a flapping rule must not fill a disk with traces.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Optional, Tuple

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.profile")

PROFILE_SERVICE = "profile"
REQUEST_NAME = "request"
RESULT_PREFIX = "result/"

# -- the cost model (factored out of bench.py) --------------------------------

# peak dense bf16 FLOP/s per chip, by jax device_kind substring
PEAK_BF16_FLOPS = [
    ("v6", 918e12),   # Trillium
    ("v5p", 459e12),
    ("v5", 197e12),   # v5e / v5 lite
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# HBM bandwidth per chip (bytes/s), same substring keys — for the
# roofline ceiling reported alongside MFU
HBM_BW = [
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]

# pure-CPU debug fallback: no published "peak" exists, but the live MFU
# plumbing must be drivable on the CPU rigs every tier-1 drill runs on —
# the exported ratio is then a plumbing signal, not a measurement
CPU_NOMINAL_PEAK_FLOPS = 1e11
CPU_NOMINAL_HBM_BW = 50e9


def peak_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOP/s for a jax ``device_kind`` (None if unknown;
    ``EDL_PEAK_FLOPS`` overrides for chips the table predates)."""
    override = os.environ.get("EDL_PEAK_FLOPS")
    if override:
        try:
            return float(override)
        except ValueError:
            logger.warning("EDL_PEAK_FLOPS=%r is not a number; ignored", override)
    kind = device_kind.lower()
    for tag, peak in PEAK_BF16_FLOPS:
        if tag in kind:
            return peak
    if "cpu" in kind:
        return CPU_NOMINAL_PEAK_FLOPS
    return None


def hbm_bandwidth(device_kind: str) -> Optional[float]:
    """HBM bandwidth (bytes/s) for a jax ``device_kind`` (None if
    unknown; ``EDL_HBM_BW`` overrides)."""
    override = os.environ.get("EDL_HBM_BW")
    if override:
        try:
            return float(override)
        except ValueError:
            logger.warning("EDL_HBM_BW=%r is not a number; ignored", override)
    kind = device_kind.lower()
    for tag, bw in HBM_BW:
        if tag in kind:
            return bw
    if "cpu" in kind:
        return CPU_NOMINAL_HBM_BW
    return None


def normalize_cost(cost) -> Dict:
    """XLA cost analysis as one flat dict (some backends return a
    one-element list); {} when unavailable."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if isinstance(cost, dict) else {}


def cost_flops(cost: Dict) -> Optional[float]:
    try:
        return float(cost.get("flops", 0.0)) or None
    except (TypeError, ValueError):
        return None


def cost_bytes(cost: Dict) -> Optional[float]:
    try:
        return (
            float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0)))
            or None
        )
    except (TypeError, ValueError):
        return None


def step_cost(step_fn, *args, **kwargs) -> Dict:
    """XLA's cost analysis for one call of a jitted ``step_fn`` at the
    given arguments — via ``Lowered.cost_analysis()``, i.e. a jax trace
    but NO XLA compile (the compile already happened, or will, through
    the jit cache). Returns {} on any failure: the cost model is
    telemetry, never a correctness dependency."""
    try:
        return normalize_cost(step_fn.lower(*args, **kwargs).cost_analysis())
    except Exception as exc:  # noqa: BLE001 — backend/API drift degrades to no cost
        logger.debug("step cost extraction failed: %s", exc)
        return {}


def roofline(cost, device_kind: str, peak: float, mfu: Optional[float] = None) -> Dict:
    """XLA-cost-model roofline for one compiled step: arithmetic
    intensity (FLOPs / HBM bytes) against the chip's compute/bandwidth
    ratio gives the MFU CEILING this program shape admits — so a
    measured MFU reads as "x of the achievable", not "x of a number the
    memory system may forbid". Uses XLA's own flops and bytes-accessed
    estimates; returns {} when either is unavailable. Pass the measured
    ``mfu`` to also get ``mfu_of_ceiling``."""
    cost = normalize_cost(cost)
    flops = cost_flops(cost)
    bytes_accessed = cost_bytes(cost)
    bw = hbm_bandwidth(device_kind)
    if not (flops and bytes_accessed and bw and peak):
        return {}
    ai = flops / bytes_accessed  # FLOPs per HBM byte
    ridge = peak / bw            # FLOPs per byte needed to be compute-bound
    ceiling = min(1.0, ai / ridge)
    out = {
        "step_hbm_gb": round(bytes_accessed / 1e9, 2),
        "arithmetic_intensity": round(ai, 1),
        "roofline_mfu_ceiling": round(ceiling, 3),
        "bound": "compute" if ai >= ridge else "memory",
    }
    if mfu is not None and ceiling:
        out["mfu_of_ceiling"] = round(mfu / ceiling, 3)
    return out


def device_memory_stats_full(device) -> Optional[Dict[str, float]]:
    """The richer ``device.memory_stats()`` dict the memory plane reads:
    always ``bytes_in_use``/``bytes_limit``, plus ``peak_bytes_in_use``
    and ``bytes_reserved`` when the backend provides them (TPU runtimes
    do; the peak is the allocator's own process-lifetime high-water mark
    — the memory plane layers its per-stage resettable watermark on
    top). None when the backend has no memory stats at all (CPU
    backends, older runtimes return None or omit the method) or reports
    no recognizable key. Never raises."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — older runtimes raise instead of None
        return None
    if not isinstance(stats, dict):
        return None
    in_use = stats.get("bytes_in_use")
    limit = stats.get("bytes_limit", stats.get("bytes_reservable_limit"))
    if in_use is None and limit is None:
        return None
    out = {
        "bytes_in_use": float(in_use or 0.0),
        "bytes_limit": float(limit or 0.0),
    }
    for key in ("peak_bytes_in_use", "bytes_reserved"):
        v = stats.get(key)
        if v is not None:
            out[key] = float(v)
    return out


def device_memory_stats(device) -> Optional[Tuple[float, float]]:
    """``(bytes_in_use, bytes_limit)`` — the 2-tuple shim over
    :func:`device_memory_stats_full` the pre-memory-plane callers (HBM
    gauges, snapshots) keep using. Never raises."""
    stats = device_memory_stats_full(device)
    if stats is None:
        return None
    return stats["bytes_in_use"], stats["bytes_limit"]


# -- live telemetry -----------------------------------------------------------


class StepTelemetry:
    """Windowed MFU / roofline / HBM gauges for one training stage.

    Created per stage by the training loop (and the chaos trainee's
    audited miniature); :meth:`set_cost` arms it with the step's cost
    analysis and the device, :meth:`observe_step` is called once per
    completed step. Scrape-time gauges are bound through
    :func:`~edl_tpu.obs.metrics.bind_gauges` so :meth:`close` releases
    them — a restaged stage must not leave the old stage's closures in
    the process-global registry.
    """

    def __init__(
        self,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        window_s: float = 60.0,
    ) -> None:
        self._reg = (
            registry if registry is not None else obs_metrics.default_registry()
        )
        self._window_s = window_s
        self._lock = threading.Lock()
        # (monotonic ts, dt) of completed steps; maxlen bounds memory at
        # high step rates against the time-based trim
        self._steps: deque = deque(maxlen=4096)
        self._last_ts: Optional[float] = None
        self._flops_per_step: Optional[float] = None
        self._peak: Optional[float] = None
        self._ceiling: Optional[float] = None
        self._device = None
        self._m_flops_total = self._reg.counter(
            "edl_train_flops_total",
            "cost-model FLOPs dispatched by completed train steps",
        )
        self._binding: Optional[obs_metrics.GaugeBinding] = None

    # -- arming ------------------------------------------------------------

    def set_cost(self, cost, device=None) -> Dict:
        """Arm the telemetry with one step's XLA cost analysis and the
        device it runs on; returns the roofline dict (possibly {}).
        Safe to call with a {} cost: only the HBM gauges (if the device
        has memory stats) are exported then."""
        cost = normalize_cost(cost)
        if device is None:
            try:
                import jax

                device = jax.devices()[0]
            except Exception:  # noqa: BLE001 — no backend: gauges stay unexported
                device = None
        kind = getattr(device, "device_kind", "") or ""
        flops = cost_flops(cost)
        peak = peak_flops(kind) if kind else None
        roof = roofline(cost, kind, peak) if peak else {}
        specs = []
        with self._lock:
            self._device = device
            self._flops_per_step = flops
            self._peak = peak
            self._ceiling = roof.get("roofline_mfu_ceiling")
        if flops:
            specs.append((
                "edl_train_step_flops",
                "cost-model FLOPs for one train step (fwd+bwd+update)",
                lambda: self._flops_per_step or 0.0,
            ))
        if flops and peak:
            specs.append((
                "edl_train_mfu_ratio",
                "windowed model-FLOPs utilization: FLOPs/step over the "
                "window's median step time, against peak (CPU backends "
                "report vs a nominal debug peak)",
                self.window_mfu,
            ))
        if roof:
            specs.append((
                "edl_train_roofline_mfu_ceiling",
                "MFU ceiling the step's arithmetic intensity admits on "
                "this chip's roofline",
                lambda: self._ceiling or 0.0,
            ))
            ai = roof.get("arithmetic_intensity", 0.0)
            specs.append((
                "edl_train_arithmetic_intensity",
                "cost-model FLOPs per HBM byte for one train step",
                lambda ai=ai: ai,
            ))
        if device is not None and device_memory_stats(device) is not None:
            # guarded: memory_stats is None/absent on CPU backends and
            # older runtimes — then these two gauges simply don't exist
            specs.append((
                "edl_device_hbm_bytes_in_use",
                "device HBM bytes currently allocated",
                lambda: (device_memory_stats(self._device) or (0.0, 0.0))[0],
            ))
            specs.append((
                "edl_device_hbm_bytes_limit",
                "device HBM capacity visible to the allocator",
                lambda: (device_memory_stats(self._device) or (0.0, 0.0))[1],
            ))
        if self._binding is not None:
            self._binding.release()
        self._binding = obs_metrics.bind_gauges(specs, self._reg) if specs else None
        return roof

    # -- per-step ----------------------------------------------------------

    def observe_step(
        self, dt: Optional[float] = None, ts: Optional[float] = None
    ) -> None:
        """Record one completed step: ``dt`` is its dispatch-to-dispatch
        wall time (derived from the previous call when omitted; both
        injectable for tests). Advances the FLOPs counter and the MFU
        window."""
        now = time.monotonic() if ts is None else ts
        with self._lock:
            if dt is None:
                dt = (now - self._last_ts) if self._last_ts is not None else 0.0
            self._last_ts = now
            if dt > 0:
                self._steps.append((now, float(dt)))
                horizon = now - self._window_s
                while self._steps and self._steps[0][0] < horizon:
                    self._steps.popleft()
            flops = self._flops_per_step
        if flops:
            self._m_flops_total.inc(flops)

    def window_mfu(self, now: Optional[float] = None) -> float:
        """Windowed MFU: FLOPs/step over the MEDIAN step time of the
        window, against peak. The median (not the span) makes one
        checkpoint pause, the compile-heavy first step, or a clock
        anomaly an outlier instead of the denominator; 0.0 until two
        steps have landed — and 0.0 again once the whole window has
        aged out (a wedged worker must read as degraded at scrape
        time, not keep exporting its last healthy ratio forever)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            flops, peak = self._flops_per_step, self._peak
            if not (flops and peak) or len(self._steps) < 2:
                return 0.0
            if now - self._steps[-1][0] > self._window_s:
                return 0.0  # nothing stepped for a full window: stale
            dts = sorted(dt for _ts, dt in self._steps)
        median = dts[len(dts) // 2]
        if median <= 0:
            return 0.0
        return flops / median / peak

    def hbm_in_use(self) -> Optional[float]:
        with self._lock:
            device = self._device
        stats = device_memory_stats(device) if device is not None else None
        return stats[0] if stats else None

    def snapshot(self) -> Dict:
        """Current telemetry as plain data (capture summaries, tests)."""
        with self._lock:
            doc = {
                "step_flops": self._flops_per_step,
                "peak_flops": self._peak,
                "roofline_mfu_ceiling": self._ceiling,
            }
        doc["mfu"] = round(self.window_mfu(), 4)
        hbm = self.hbm_in_use()
        if hbm is not None:
            doc["hbm_bytes_in_use"] = hbm
        return {k: v for k, v in doc.items() if v is not None}

    def close(self) -> None:
        if self._binding is not None:
            self._binding.release()
            self._binding = None


# -- on-demand capture --------------------------------------------------------


def profile_prefix(job_id: str) -> str:
    return "/%s/%s/" % (job_id, PROFILE_SERVICE)


def request_capture(
    client,
    job_id: str,
    steps: int = 5,
    reason: str = "manual",
    request_id: Optional[str] = None,
    out_dir: Optional[str] = None,
) -> str:
    """Publish a capture request every worker of the job will honor;
    returns the request id (monotonic-ish, unique per requester)."""
    rid = request_id or "%d.%d" % (int(time.time() * 1000), os.getpid())
    doc = {"id": rid, "steps": int(steps), "reason": reason, "ts": time.time()}
    if out_dir:
        doc["dir"] = out_dir
    client.put(profile_prefix(job_id) + REQUEST_NAME, json.dumps(doc).encode())
    return rid


def read_results(
    client, job_id: str, request_id: Optional[str] = None
) -> Dict[str, Dict]:
    """Published capture results ``{pod[.rank]: summary}``, optionally
    filtered to one request id."""
    out: Dict[str, Dict] = {}
    prefix = profile_prefix(job_id) + RESULT_PREFIX
    try:
        rows, _rev = client.range(prefix)
    except Exception as exc:  # noqa: BLE001 — a dead store reads as no results
        logger.warning("profile result read failed: %s", exc)
        return out
    for key, value, _c, _m in rows:
        try:
            doc = json.loads(value)
        except ValueError:
            continue
        if request_id is None or doc.get("id") == request_id:
            out[key[len(prefix):]] = doc
    return out


class CaptureController:
    """Worker-side state machine for store-driven profiler windows.

    The training loop calls :meth:`on_step` once per completed step; the
    controller starts a ``jax.profiler`` trace when a new
    ``profile/request`` appears (or when the legacy ``EDL_PROFILE_DIR``
    window armed via :meth:`arm_local` comes due), stops it after the
    requested number of steps, and publishes ``profile/result/{pod}``
    with the artifact path and the window summary. Everything is
    best-effort and exception-contained: profiling must never take down
    the step loop it observes.
    """

    def __init__(
        self,
        env,
        telemetry: Optional[StepTelemetry] = None,
        client=None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ) -> None:
        self._env = env
        self._telemetry = telemetry
        reg = registry if registry is not None else obs_metrics.default_registry()
        self._m_captures = reg.counter(
            "edl_profile_captures_total",
            "completed profiler capture windows, by trigger",
        )
        self._lock = threading.Lock()
        self._pending: Optional[Dict] = None
        self._done_ids: set = set()
        self._local: Optional[Dict] = None
        self._steps_until_local = 0
        self._tracing: Optional[Dict] = None
        self._calls = 0
        self._owns_client = False
        self._client = client
        self._watch = None
        if self._client is None and getattr(env, "store_endpoint", ""):
            try:
                from edl_tpu.store.client import connect_store

                self._client = connect_store(env.store_endpoint, timeout=2.0)
                self._owns_client = True
            except Exception as exc:  # noqa: BLE001
                logger.warning("capture controller has no store: %s", exc)
        if self._client is not None and getattr(env, "job_id", ""):
            # seed the done-set with the request this worker already
            # answered in a previous incarnation: a restaged worker must
            # not re-run a capture its published result proves done. The
            # seed is best-effort — a store blip here must not leave the
            # worker deaf to requests for the whole stage, so the watch
            # below is installed regardless.
            try:
                prior = self._client.get(
                    profile_prefix(env.job_id) + RESULT_PREFIX + self._result_name()
                )
                if prior:
                    self._done_ids.add(json.loads(prior).get("id"))
            except Exception as exc:  # noqa: BLE001 — unseeded is recoverable
                logger.warning("capture done-set seed unavailable: %s", exc)
            try:
                from edl_tpu.discovery.registry import Registry

                self._registry = Registry(self._client, env.job_id)
                self._watch = self._registry.watch_service(
                    PROFILE_SERVICE, on_change=self._on_change
                )
            except Exception as exc:  # noqa: BLE001
                logger.warning("capture request watch unavailable: %s", exc)
                self._watch = None

    # -- request intake ----------------------------------------------------

    def _result_name(self) -> str:
        pod = getattr(self._env, "pod_id", "") or "pod"
        rank = int(getattr(self._env, "rank_in_pod", 0) or 0)
        return pod if rank == 0 else "%s.%d" % (pod, rank)

    def _on_change(self, snapshot) -> None:
        meta = snapshot.get(REQUEST_NAME)
        if meta is None:
            return
        try:
            doc = json.loads(meta.value)
        except ValueError:
            return
        rid = doc.get("id")
        with self._lock:
            if not rid or rid in self._done_ids:
                return
            self._pending = doc

    def arm_local(self, out_dir: str, start_after: int = 10, steps: int = 5) -> None:
        """The legacy env-armed window (``EDL_PROFILE_DIR``): one capture
        of ``steps`` steps beginning after ``start_after`` completed
        steps, published like a store request (when a store is around)."""
        with self._lock:
            self._local = {
                "id": "local.%d" % os.getpid(), "steps": int(steps),
                "reason": "env", "dir": out_dir,
            }
            self._steps_until_local = int(start_after)

    @property
    def tracing(self) -> bool:
        with self._lock:
            return self._tracing is not None

    # -- the per-step hook -------------------------------------------------

    def on_step(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Advance the state machine by one completed step. ``sync`` is
        called (e.g. ``block_until_ready`` on the step's metrics) before
        a window closes, so the trace contains the device work it
        claims to."""
        try:
            self._on_step(sync)
        except Exception as exc:  # noqa: BLE001 — never take down the step loop
            logger.warning("capture controller step failed: %s", exc)
            with self._lock:
                self._tracing = None

    def _on_step(self, sync) -> None:
        with self._lock:
            self._calls += 1
            tracing = self._tracing
            if tracing is None:
                request = None
                if self._pending is not None:
                    request, self._pending = self._pending, None
                    if request.get("id") in self._done_ids:
                        # a result publication (any pod's) re-fires the
                        # service watch, and _on_change may re-arm a
                        # request THIS worker was still tracing at the
                        # time — done is done, never capture it twice
                        request = None
                if request is None and (
                    self._local is not None
                    and self._calls > self._steps_until_local
                ):
                    request, self._local = self._local, None
                if request is None:
                    return
        if tracing is not None:
            tracing["steps_seen"] += 1
            if tracing["steps_seen"] >= tracing["want"]:
                self._finish(tracing, sync)
            return
        self._begin(request)

    def _begin(self, request: Dict) -> None:
        out_dir = request.get("dir") or os.environ.get(
            "EDL_PROFILE_OUT",
            os.path.join(tempfile.gettempdir(), "edl_profile"),
        )
        job = getattr(self._env, "job_id", "") or "job"
        rid = str(request.get("id", "r"))
        trace_dir = os.path.join(
            out_dir, job, rid.replace("/", "_"), self._result_name()
        )
        os.makedirs(trace_dir, exist_ok=True)
        import jax

        jax.profiler.start_trace(trace_dir)
        tracing = {
            "id": rid,
            "want": max(1, int(request.get("steps", 5))),
            "steps_seen": 0,
            "reason": str(request.get("reason", "manual")),
            "dir": trace_dir,
            "t0": time.time(),
            "t0_mono": time.monotonic(),
        }
        with self._lock:
            self._tracing = tracing
        obs_events.record(
            "profile", fsync=True, phase="start", id=rid, dir=trace_dir,
            reason=tracing["reason"],
        )
        logger.info(
            "profiler capture %s started (%d steps) -> %s",
            rid, tracing["want"], trace_dir,
        )

    def _finish(self, tracing: Dict, sync) -> None:
        if sync is not None:
            try:
                sync()
            except Exception:  # noqa: BLE001 — a failed sync still stops the trace
                pass
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001
            logger.warning("profiler stop_trace failed: %s", exc)
        t1, t1_mono = time.time(), time.monotonic()
        span = max(1e-9, t1_mono - tracing["t0_mono"])
        doc = {
            "id": tracing["id"],
            "pod": getattr(self._env, "pod_id", "") or "",
            "rank": int(getattr(self._env, "global_rank", 0) or 0),
            "reason": tracing["reason"],
            "dir": tracing["dir"],
            "steps": tracing["steps_seen"],
            "t0": tracing["t0"],
            "t1": t1,
            "step_ms": round(span / tracing["steps_seen"] * 1e3, 3),
        }
        if self._telemetry is not None:
            doc.update(
                {
                    k: v
                    for k, v in self._telemetry.snapshot().items()
                    if k in ("mfu", "hbm_bytes_in_use", "step_flops",
                             "roofline_mfu_ceiling")
                }
            )
        with self._lock:
            self._done_ids.add(tracing["id"])
            self._tracing = None
        self._m_captures.inc(trigger=tracing["reason"])
        obs_events.record(
            "profile", fsync=True, phase="done", id=tracing["id"],
            dir=tracing["dir"], steps=doc["steps"], t0=tracing["t0"],
            step_ms=doc["step_ms"], reason=tracing["reason"],
            mfu=doc.get("mfu"),
        )
        job = getattr(self._env, "job_id", "")
        if self._client is not None and job:
            key = profile_prefix(job) + RESULT_PREFIX + self._result_name()
            try:  # fire-and-forget, like every telemetry writer
                self._client.put(key, json.dumps(doc).encode())
            except Exception as exc:  # noqa: BLE001
                logger.warning("profile result not published: %s", exc)
        logger.info(
            "profiler capture %s done: %d steps, %.2f ms/step -> %s",
            tracing["id"], doc["steps"], doc["step_ms"], tracing["dir"],
        )

    def close(self) -> None:
        with self._lock:
            tracing, self._tracing = self._tracing, None
        if tracing is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
        if self._watch is not None:
            try:
                self._watch.cancel()
            except Exception:  # noqa: BLE001
                pass
        if self._owns_client and self._client is not None:
            self._client.close()
            self._client = None


# -- alert-triggered snapshots ------------------------------------------------


class AutoCapture:
    """Monitor-side ``on_fire`` action: one capture request per alert
    firing, bounded by a per-job cooldown and a lifetime cap.

    Pass an instance as ``Monitor(..., on_fire=AutoCapture(client, job))``
    (``tools/edl_monitord.py --auto-capture`` wires it). Only the rules
    in ``rules`` trigger; everything is fire-and-forget.
    """

    DEFAULT_RULES = ("goodput-degraded", "mfu-degraded")

    def __init__(
        self,
        client,
        job_id: str,
        rules: Iterable[str] = DEFAULT_RULES,
        cooldown_s: float = 300.0,
        max_captures: int = 5,
        steps: int = 5,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ) -> None:
        self._client = client
        self._job_id = job_id
        self._rules = frozenset(rules)
        self._cooldown_s = cooldown_s
        self._max = max_captures
        self._steps = steps
        self._last_ts: Optional[float] = None
        self._count = 0
        self._lock = threading.Lock()
        reg = registry if registry is not None else obs_metrics.default_registry()
        self._m_requests = reg.counter(
            "edl_monitor_capture_requests_total",
            "profiler captures auto-requested on alert firings, by rule",
        )

    def __call__(self, rule, doc: Dict) -> None:
        name = getattr(rule, "name", str(rule))
        if name not in self._rules:
            return
        now = float(doc.get("ts") or time.time())
        with self._lock:
            if self._count >= self._max:
                logger.info(
                    "auto-capture cap reached (%d); %s firing not captured",
                    self._max, name,
                )
                return
            if self._last_ts is not None and now - self._last_ts < self._cooldown_s:
                return
            # the slot and cooldown commit only on a successful request:
            # alerts tend to fire exactly when the store is in trouble,
            # and N transient put failures must not spend the lifetime
            # cap without ever producing a capture
            try:
                rid = request_capture(
                    self._client, self._job_id, steps=self._steps, reason=name
                )
            except Exception as exc:  # noqa: BLE001 — never take down the monitor
                logger.warning("auto-capture request failed: %s", exc)
                return
            self._last_ts = now
            self._count += 1
        self._m_requests.inc(rule=name)
        logger.warning(
            "auto-capture %s requested on %s firing (%d/%d used)",
            rid, name, self._count, self._max,
        )
