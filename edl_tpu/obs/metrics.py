"""Process-local metrics registry: counters, gauges, histograms.

The reference has no metrics plane at all — its elastic claims are
wall-clock demos (README.md:96-151) and its only live signal is a
stderr profiler. This registry is the in-process half of the edl_tpu
observability layer: every long-lived process (store server, launcher,
data dispatcher, distill teacher, train worker) registers instruments
here and mounts :class:`edl_tpu.obs.http.ObsServer` to serve them as
Prometheus text.

Naming convention (lint-enforced by tests/test_obs.py): every metric is
``edl_<component>_<name>_<unit>`` — lowercase, underscore-separated, at
least three segments after the ``edl`` prefix counts as two (component +
name-with-unit). Counters end in ``_total`` per Prometheus convention
(``total`` is the unit segment); durations end in ``_seconds``, sizes in
``_bytes``, depths in ``_depth``/``_tasks``.

Instruments are get-or-create by name (a process has ONE instrument per
name regardless of how many objects instrument it) and observation is
fire-and-forget cheap: a lock + dict update, no I/O — observability must
never take down (or slow down) the thing it observes. Labeled children
(``counter(...).labels(method="put")``) pre-resolve the label lookup so
hot paths pay one dict hit per observation, not a tuple build.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# edl_<component>_<name>_<unit>: >= 3 underscore segments after "edl"
# would be ideal, but component/name/unit are each >= 1 segment, so the
# enforceable floor is edl_ + two more segments, all [a-z0-9].
METRIC_NAME_RE = re.compile(r"^edl(_[a-z][a-z0-9]*){2,}$")

# Default duration buckets (seconds): micro-RPCs to multi-minute
# checkpoint writes on one fixed grid, so cross-process histograms merge.
DURATION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)
# Default size buckets (bytes): 64B frames to multi-GB checkpoints.
SIZE_BUCKETS = tuple(float(1 << p) for p in range(6, 33, 2))

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _format_value(v: float) -> str:
    v = float(v)
    # Prometheus text spellings for non-finite values — int(nan) raises,
    # and one poisoned observation must not break every future scrape
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _render_labels(key: LabelKey, extra: str = "") -> str:
    parts = ['%s="%s"' % (k, _escape_label(v)) for k, v in key]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{%s}" % ",".join(parts)


class _Instrument:
    """Shared base: named, helped, thread-safe, optionally labeled."""

    kind = "untyped"

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonically increasing count. ``inc(n, **labels)``."""

    kind = "counter"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError("counter %s cannot decrease" % self.name)
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def labels(self, **labels: str) -> "_BoundCounter":
        return _BoundCounter(self, _label_key(labels))

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        return [
            "%s%s %s" % (self.name, _render_labels(k), _format_value(v))
            for k, v in items
        ]


class _BoundCounter:
    """Label-resolved counter child: one dict hit per inc, no tuple
    build — for per-frame hot paths (rpc/wire.py)."""

    __slots__ = ("_counter", "_key")

    def __init__(self, counter: Counter, key: LabelKey) -> None:
        self._counter = counter
        self._key = key

    def inc(self, n: float = 1.0) -> None:
        c = self._counter
        with c._lock:
            c._values[self._key] = c._values.get(self._key, 0.0) + n


class Gauge(_Instrument):
    """Point-in-time value. ``set``/``inc``/``dec``, or ``set_fn`` a
    zero-arg callable sampled at render time (queue depths, connection
    counts — the value lives in the owning object, not the metric)."""

    kind = "gauge"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels: str) -> None:
        self.inc(-n, **labels)

    def set_fn(self, fn: Callable[[], float]) -> "Gauge":
        """Sample ``fn()`` at render time (unlabeled series only)."""
        with self._lock:
            self._fn = fn
        return self

    def clear_fn(self, fn: Optional[Callable[[], float]] = None) -> None:
        """Drop the render-time callback — owners MUST call this on stop,
        or the process-global registry pins them (and whatever their
        closure reaches, e.g. queued batches) alive forever. With ``fn``
        given, clears only if it is still the registered one, so a
        stopping instance never strips its replacement's callback."""
        with self._lock:
            if fn is None or self._fn is fn:
                self._fn = None

    def value(self, **labels: str) -> float:
        with self._lock:
            fn = self._fn
        if fn is not None and not labels:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead owner must not kill render
                return float("nan")
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
            fn = self._fn
        if fn is not None:
            try:
                items = [((), float(fn()))] + [i for i in items if i[0]]
            except Exception:  # noqa: BLE001
                pass
        if not items:
            items = [((), 0.0)]
        return [
            "%s%s %s" % (self.name, _render_labels(k), _format_value(v))
            for k, v in items
        ]


class Histogram(_Instrument):
    """Fixed-bucket histogram: cumulative bucket counts + sum + count.

    Buckets are fixed at registration (cross-process merges need one
    grid); observation is O(buckets) increments under the lock.
    """

    kind = "histogram"

    def __init__(
        self, name: str, help: str, buckets: Sequence[float] = DURATION_BUCKETS
    ) -> None:
        super().__init__(name, help)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram %s needs at least one bucket" % name)
        self.buckets: Tuple[float, ...] = tuple(bs)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._totals: Dict[LabelKey, int] = {}

    def observe(self, v: float, **labels: str) -> None:
        self._observe_key(_label_key(labels), float(v))

    def _observe_key(self, key: LabelKey, v: float) -> None:
        """The one locked observation body, shared with
        :class:`_BoundHistogram` so the labeled and direct paths can
        never diverge."""
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * len(self.buckets)
                self._sums[key] = 0.0
                self._totals[key] = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            self._sums[key] += v
            self._totals[key] += 1

    def time(self, **labels: str) -> "_Timer":
        """``with hist.time(): ...`` observes the block's wall time."""
        return _Timer(self, labels)

    def labels(self, **labels: str) -> "_BoundHistogram":
        """Label-resolved child: per-observation cost is the lock + the
        bucket scan, no tuple build — the per-frame discipline of
        ``_BoundCounter``, for rpc/wire.py's server-side histogram."""
        return _BoundHistogram(self, _label_key(labels))

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(_label_key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            keys = sorted(self._counts) or [()]
            snap = {
                k: (list(self._counts.get(k, [])), self._sums.get(k, 0.0),
                    self._totals.get(k, 0))
                for k in keys
            }
        out: List[str] = []
        for key in keys:
            counts, total_sum, total = snap[key]
            if not counts:
                counts = [0] * len(self.buckets)
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out.append(
                    "%s_bucket%s %d"
                    % (self.name, _render_labels(key, 'le="%s"' % _format_value(b)), cum)
                )
            out.append(
                "%s_bucket%s %d"
                % (self.name, _render_labels(key, 'le="+Inf"'), total)
            )
            out.append(
                "%s_sum%s %s" % (self.name, _render_labels(key), _format_value(total_sum))
            )
            out.append("%s_count%s %d" % (self.name, _render_labels(key), total))
        return out


class _BoundHistogram:
    """Label-resolved histogram child (see :meth:`Histogram.labels`):
    per-observation cost is the shared locked body, no tuple build."""

    __slots__ = ("_hist", "_key")

    def __init__(self, hist: Histogram, key: LabelKey) -> None:
        self._hist = hist
        self._key = key

    def observe(self, v: float) -> None:
        self._hist._observe_key(self._key, float(v))


class _Timer:
    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: Histogram, labels: Dict[str, str]) -> None:
        self._hist = hist
        self._labels = labels

    def __enter__(self) -> "_Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.monotonic() - self._t0, **self._labels)


class MetricsRegistry:
    """Thread-safe instrument registry with Prometheus text rendering.

    Get-or-create semantics: registering an existing name returns the
    existing instrument (type mismatch raises — two subsystems fighting
    over one name is a bug, not a race to tolerate).
    """

    def __init__(self, validate_names: bool = True) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        self._validate = validate_names

    def _register(self, cls, name: str, help: str, **kwargs) -> _Instrument:
        if self._validate and not METRIC_NAME_RE.match(name):
            raise ValueError(
                "metric name %r violates the edl_<component>_<name>_<unit> "
                "convention (%s)" % (name, METRIC_NAME_RE.pattern)
            )
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        "metric %r already registered as %s, not %s"
                        % (name, inst.kind, cls.kind)
                    )
                return inst
            inst = cls(name, help, **kwargs)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DURATION_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def render(self) -> str:
        """The full registry as Prometheus exposition text (version 0.0.4)."""
        with self._lock:
            instruments = [self._instruments[n] for n in sorted(self._instruments)]
        lines: List[str] = []
        for inst in instruments:
            if inst.help:
                lines.append("# HELP %s %s" % (inst.name, inst.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (inst.name, inst.kind))
            lines.extend(inst.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Scalar view for JSON consumers (healthz, edl-top): name ->
        {rendered-series-suffix: value}; histograms report _count/_sum."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            if isinstance(inst, Histogram):
                with inst._lock:
                    out[inst.name] = {
                        "count": float(sum(inst._totals.values())),
                        "sum": float(sum(inst._sums.values())),
                    }
            elif isinstance(inst, (Counter, Gauge)):
                series: Dict[str, float] = {}
                for line in inst.render():
                    name_part, _, value = line.rpartition(" ")
                    series[name_part[len(inst.name):] or ""] = float(value)
                out[inst.name] = series
        return out


class GaugeBinding:
    """Owned set of callback gauges with a single release point.

    The registry is process-global, so a ``set_fn`` closure pins its
    owner (and everything the closure reaches — queues, store state)
    until replaced. This helper makes the pairing impossible to get
    wrong: bind at construction, ``release()`` at stop (identity-guarded
    per gauge, so a replacement instance that already rebound is left
    alone).
    """

    def __init__(
        self,
        specs: Iterable[Tuple[str, str, Callable[[], float]]],
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        reg = registry if registry is not None else _default
        self._bound: List[Tuple[Gauge, Callable[[], float]]] = []
        for name, help_text, fn in specs:
            gauge = reg.gauge(name, help_text)
            gauge.set_fn(fn)
            self._bound.append((gauge, fn))

    def release(self) -> None:
        for gauge, fn in self._bound:
            gauge.clear_fn(fn)


def bind_gauges(
    specs: Iterable[Tuple[str, str, Callable[[], float]]],
    registry: Optional[MetricsRegistry] = None,
) -> GaugeBinding:
    """Register ``(name, help, fn)`` callback gauges; release() on stop."""
    return GaugeBinding(specs, registry)


# -- scrape-side helpers ------------------------------------------------------

_LE_RE = re.compile(r'le="([^"]+)"')


def quantile_from_grid(grid: Dict[float, float], q: float) -> Optional[float]:
    """Estimate quantile ``q`` from one cumulative ``{le: count}`` grid,
    interpolating linearly inside the winning bucket — the classic
    Prometheus ``histogram_quantile`` estimator. Returns None on an
    empty grid or zero observations; the open ``+Inf`` bucket reports
    its lower bound (the largest finite edge)."""
    if not grid:
        return None
    edges = sorted(grid)
    total = grid[edges[-1]]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for edge in edges:
        cum = grid[edge]
        if cum >= target:
            if edge == float("inf"):
                return prev_edge  # open bucket: report its lower bound
            if cum == prev_cum:
                return edge
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_edge + frac * (edge - prev_edge)
        prev_edge, prev_cum = edge, cum
    return edges[-1]


def bucket_grid(
    series: Dict[str, float], label_substr: str = ""
) -> Dict[float, float]:
    """Collapse a scraped ``{name}_bucket`` label map onto one cumulative
    ``{le: count}`` grid, summing every label set that contains
    ``label_substr`` (same filter convention as the chaos invariants)."""
    grid: Dict[float, float] = {}
    for labels, value in series.items():
        m = _LE_RE.search(labels)
        if not m or label_substr not in labels:
            continue
        le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
        grid[le] = grid.get(le, 0.0) + value
    return grid


def histogram_quantile(
    metrics: Dict[str, Dict[str, float]], name: str, q: float
) -> Optional[float]:
    """Estimate quantile ``q`` of histogram ``name`` from a scraped
    metrics dict (``obs.http.fetch_metrics`` shape), aggregating every
    label set onto one cumulative grid. Shared by the ``edl-top``
    hb_p50/hb_p95 columns and the monitor plane's staleness rules — one
    tested implementation instead of per-tool copies."""
    buckets = metrics.get(name + "_bucket")
    if not buckets:
        return None
    return quantile_from_grid(bucket_grid(buckets), q)


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    """Get-or-create a counter in the process-default registry."""
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    """Get-or-create a gauge in the process-default registry."""
    return _default.gauge(name, help)


def histogram(
    name: str, help: str = "", buckets: Sequence[float] = DURATION_BUCKETS
) -> Histogram:
    """Get-or-create a histogram in the process-default registry."""
    return _default.histogram(name, help, buckets)
