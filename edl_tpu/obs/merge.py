"""Merge per-process Chrome traces into ONE job timeline.

Every instrumented process exports ``{component}-{pid}.trace.json`` into
``EDL_TRACE_DIR`` (see :mod:`edl_tpu.obs.trace`). This tool splices them:
span timestamps are already unix-epoch-anchored, so alignment is a
common-origin rebase (earliest event across all files becomes t=0 —
Perfetto renders relative microseconds far more readably than 52-bit
epoch values), and pid collisions across hosts are resolved by remapping
each file to its own pid namespace while keeping the component name as
the process label.

Usage::

    python -m edl_tpu.obs.merge --dir /tmp/traces -o job.trace.json
    python -m edl_tpu.obs.merge a.trace.json b.trace.json -o job.trace.json

The output loads in ``chrome://tracing`` / https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

from edl_tpu.utils.log import get_logger

logger = get_logger("obs.merge")


def _load(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return list(doc.get("traceEvents", []))
    if isinstance(doc, list):  # bare event-array form is also legal
        return doc
    raise ValueError("%s is not a Chrome trace" % path)


def merge_traces(paths: Iterable[str], rebase: bool = True) -> dict:
    """Merge trace files into one document; returns the merged dict.

    Files that fail to parse are skipped with a warning (a torn export
    from a killed worker must not hide every other process's timeline).
    """
    merged: List[dict] = []
    origin: Optional[float] = None
    per_file: List[tuple] = []
    for idx, path in enumerate(paths):
        try:
            events = _load(path)
        except (OSError, ValueError) as exc:
            logger.warning("skipping %s: %s", path, exc)
            continue
        per_file.append((idx, path, events))
        for ev in events:
            ts = ev.get("ts")
            if isinstance(ts, (int, float)) and ev.get("ph") != "M":
                origin = ts if origin is None else min(origin, ts)
    if not rebase:
        origin = None
    for idx, path, events in per_file:
        # one pid namespace per file: two hosts' pid 4242 must not
        # interleave into one fake process lane
        pid_map: Dict = {}
        label = os.path.basename(path).replace(".trace.json", "")
        for ev in events:
            ev = dict(ev)
            orig_pid = ev.get("pid", 0)
            if orig_pid not in pid_map:
                pid_map[orig_pid] = (idx + 1) * 100000 + (
                    orig_pid % 100000 if isinstance(orig_pid, int) else 0
                )
            ev["pid"] = pid_map[orig_pid]
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                name = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": "%s [%s]" % (name or label, label)}
            elif origin is not None and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] - origin
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    doc = {"traceEvents": merged, "displayTimeUnit": "ms"}
    if origin is not None:
        doc["otherData"] = {"epoch_origin_us": origin}
    return doc


def expand_inputs(inputs: List[str], trace_dir: Optional[str]) -> List[str]:
    paths = list(inputs)
    if trace_dir:
        paths.extend(sorted(glob.glob(os.path.join(trace_dir, "*.trace.json"))))
    return paths


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.obs.merge",
        description="merge per-process edl_tpu traces into one Chrome trace",
    )
    parser.add_argument("traces", nargs="*", help="trace files to merge")
    parser.add_argument(
        "--dir", default=os.environ.get("EDL_TRACE_DIR"),
        help="also merge every *.trace.json here (default: $EDL_TRACE_DIR)",
    )
    parser.add_argument("-o", "--output", required=True)
    parser.add_argument(
        "--no-rebase", action="store_true",
        help="keep absolute unix-epoch microsecond timestamps",
    )
    args = parser.parse_args(argv)
    paths = expand_inputs(args.traces, args.dir)
    if not paths:
        print("no trace files found", file=sys.stderr)
        return 2
    doc = merge_traces(paths, rebase=not args.no_rebase)
    n_procs = len({e["pid"] for e in doc["traceEvents"]})
    with open(args.output, "w") as f:
        json.dump(doc, f)
    print(
        "merged %d file(s), %d events, %d process(es) -> %s"
        % (len(paths), len(doc["traceEvents"]), n_procs, args.output),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
