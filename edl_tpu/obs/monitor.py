"""Monitor plane: scrape-and-retain time series + SLO rule engine.

PR 1 gave every process a ``/metrics`` endpoint and PR 5 priced every
second of wall-clock — but nothing *watched* the measurements:
``edl-top`` renders the latest scrape and forgets it. This module is the
sensor half of closing the loop: a :class:`Monitor` discovers every
scrape target from the job's ``obs/`` store keyspace (the same
discovery ``edl-top`` uses), scrapes on an interval, **retains** the
samples — in memory for rule evaluation and, when ``monitor_dir`` /
``EDL_MONITOR_DIR`` is set, as crash-safe append-only ring segments
(``*.series.jsonl``, the :class:`~edl_tpu.obs.events.FlightRecorder`
design under a monitor-owned suffix) — and evaluates a declarative rule
set over the retained window. A goodput-driven autoscaler only has to
subscribe to the alerts this plane publishes; it never scrapes anything
itself.

Rule kinds (see :class:`Rule`):

- ``threshold`` — latest value per target violates ``op value``
  (``edl_goodput_ratio < 0.7``), sustained ``for_s`` seconds;
- ``rate``      — the job-level per-second increase of a counter over
  ``window_s`` violates ``op value``
  (``rate(edl_launch_straggler_ejections_total) > 0``); with
  ``require_advance`` the rule arms only after the series has been seen
  advancing, so a job that never trained cannot "degrade";
- ``quantile``  — quantile ``q`` of a histogram's *windowed delta*
  (observations added during the window) violates ``op value`` — the
  staleness rule over ``edl_train_step_heartbeat_age_seconds`` rides the
  shared :func:`~edl_tpu.obs.metrics.histogram_quantile` grid math;
- ``absent``    — a target that has been scraped alive before has been
  silent for more than ``stale_s`` (dead endpoint);
- ``restart``   — a target's ``edl_process_start_time_seconds`` jumped
  between samples: the process behind the registration was replaced —
  distinguishing a *restarted* process from a *wedged* one (whose start
  time is stable while its heartbeats go silent);
- ``zscore``    — the target's newest value sits ``op value`` standard
  deviations from the trailing window history (consecutive duplicate
  scrapes of a throttled gauge deduped, std floored at 5% of the mean's
  magnitude, at least 6 distinct finite points required, a non-finite
  newest value reads as an unbounded z) — the ``loss-spike`` detector;
  blind or flat windows never fire.

Firing semantics are hysteresis-bounded: a rule must hold continuously
for ``for_s`` before it fires and be clear for ``resolve_s`` before it
resolves. Every transition publishes an alert record to the store's
``alerts/{rule}`` keyspace (severity, firing/resolved, evidence
samples, full firing history), increments
``edl_monitor_alerts_total{rule,severity}``, and is flight-recorded so
``edl-timeline`` overlays alert transitions on the goodput lanes. A job
whose ``job/status`` key reads COMPLETE is *done, not degraded*: the
monitor suppresses evaluation and resolves anything still firing —
completion must never page anyone.

Run it: ``python -m tools.edl_monitord --store HOST:PORT --job ID``.
Conformance: the chaos rig runs a Monitor inside every scenario;
``worker-kill``/``preempt-drain`` must fire ``goodput-degraded`` within
a bounded latency and the ``monitor-clean`` control run must fire
nothing (``alerts_fired`` / ``no_false_alerts`` invariants).
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.monitor")

ALERTS_SERVICE = "alerts"
ENV_DIR = "EDL_MONITOR_DIR"
SERIES_SUFFIX = ".series.jsonl"
SELF_TARGET = "monitor"
_OPS: Dict[str, Callable[[float, float], bool]] = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}
_KINDS = ("threshold", "rate", "quantile", "absent", "restart", "zscore")
_ZSCORE_MIN_POINTS = 6  # distinct finite history points before a z is trusted
_FIRINGS_KEPT = 32  # firing timestamps retained in the published record


def alerts_prefix(job_id: str) -> str:
    return "/%s/%s/" % (job_id, ALERTS_SERVICE)


def read_alerts(client, job_id: str) -> Dict[str, Dict]:
    """Read back ``{rule: alert-record}`` for a job (empty dict when the
    monitor never fired anything — records exist only after a first
    firing)."""
    out: Dict[str, Dict] = {}
    prefix = alerts_prefix(job_id)
    try:
        rows, _rev = client.range(prefix)
    except Exception as exc:  # noqa: BLE001 — a dead store reads as no alerts
        logger.warning("alert read failed: %s", exc)
        return out
    for key, value, _c, _m in rows:
        try:
            out[key[len(prefix):]] = json.loads(value)
        except ValueError:
            continue
    return out


@dataclasses.dataclass
class Rule:
    """One declarative SLO rule (see the module docstring for kinds)."""

    name: str
    kind: str = "threshold"
    metric: str = ""         # series the rule watches ("" for absent rules)
    labels: str = ""         # label substring filter, e.g. 'state="train"'
    op: str = "<"
    value: float = 0.0
    q: float = 0.95          # quantile rules
    for_s: float = 0.0       # condition must hold this long before firing
    resolve_s: float = 0.0   # condition must clear this long before resolving
    window_s: float = 60.0   # rate/quantile evaluation window
    stale_s: float = 30.0    # absent rules: silence bound
    forget_s: float = 0.0    # absent rules: silence after which a target is
    #                          RETIRED (a legitimate departure — downsize,
    #                          graceful drain — must not page forever);
    #                          0 = 20 * stale_s
    target: str = ""         # substring filter on target names ("" = all)
    severity: str = "warning"
    require_advance: bool = False  # rate rules: arm only after the series moved

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(
                "rule %r: unknown kind %r (have: %s)"
                % (self.name, self.kind, ", ".join(_KINDS))
            )
        if self.op not in _OPS:
            raise ValueError(
                "rule %r: unknown op %r (have: %s)"
                % (self.name, self.op, ", ".join(_OPS))
            )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, doc: Dict) -> "Rule":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - fields)
        if unknown:
            raise ValueError("rule %r: unknown keys %s" % (doc.get("name"), unknown))
        if "name" not in doc:
            raise ValueError("rule without a name: %r" % (doc,))
        return cls(**doc)


def builtin_rules() -> List[Rule]:
    """The built-in rule pack: the signals a goodput-driven autoscaler
    needs, with production-paced defaults (the chaos rig re-paces them
    for CPU-rig time budgets). Every rule's metric must have a DESIGN.md
    catalogue row — lint-enforced by tests/test_monitor.py."""
    return [
        Rule(
            "goodput-degraded", kind="rate",
            metric="edl_goodput_seconds_total", labels='state="train"',
            op="<", value=0.05, window_s=30.0, for_s=30.0,
            severity="critical", require_advance=True,
        ),
        Rule(
            # the on-device twin of goodput-degraded: goodput prices
            # SECONDS, this rule prices WORK — a job whose cost-model
            # FLOP dispatch rate collapsed after having dispatched is
            # stepping uselessly (or not at all) even if wall-clock
            # still reads "train". The profiling plane's auto-capture
            # answers the firing with an on-device trace of the window.
            "mfu-degraded", kind="rate",
            metric="edl_train_flops_total",
            op="<", value=1.0, window_s=30.0, for_s=30.0,
            severity="warning", require_advance=True,
        ),
        Rule(
            "straggler-ejections", kind="rate",
            metric="edl_launch_straggler_ejections_total",
            op=">", value=0.0, window_s=120.0, severity="warning",
        ),
        Rule(
            "replication-lag", kind="threshold",
            metric="edl_store_replication_lag_entries",
            op=">", value=64.0, for_s=15.0, severity="warning",
        ),
        Rule(
            # the semi-sync escape hatch engaged: a primary is acking
            # commits WITHOUT standby durability (standby too slow or
            # its link dead) — the exact loss window semi-sync exists
            # to close is open again, and store-failover is no longer
            # lossless until the standby catches back up
            "repl-sync-degraded", kind="rate",
            metric="edl_store_repl_sync_degraded_total",
            op=">", value=0.0, window_s=120.0, severity="warning",
        ),
        Rule(
            "ckpt-restore-fallbacks", kind="rate",
            metric="edl_ckpt_restore_fallbacks_total",
            op=">", value=0.0, window_s=120.0, severity="warning",
        ),
        Rule(
            # the peer-replication plane's freshness signal: the saver
            # is accruing checkpoints its peers do not hold — lose this
            # pod now and recovery falls to the durable backstop with
            # that many steps of extra lost work. Fires on sustained
            # lag only (a push in flight right after a save is normal).
            "ckpt-replica-stale", kind="threshold",
            metric="edl_ckpt_replica_lag_steps",
            op=">", value=8.0, for_s=60.0, severity="warning",
        ),
        Rule(
            "distill-queue-saturated", kind="threshold",
            metric="edl_distill_task_queue_depth",
            op=">=", value=64.0, for_s=15.0, severity="warning",
        ),
        Rule(
            # the serving plane's overload signal: teachers refusing
            # work at a sustained rate. Occasional sheds are the
            # admission test doing its job under a burst; a sustained
            # rate means offered load exceeds fleet capacity and the
            # autoscaler (or the operator) owes the fleet teachers.
            # require_advance: the counter registers at 0 with the
            # first served request — only real sheds arm the window.
            "serve-shed-rate", kind="rate",
            metric="edl_distill_shed_total",
            op=">", value=1.0, window_s=60.0, for_s=30.0,
            severity="warning", require_advance=True,
        ),
        Rule(
            # a client-side circuit breaker is OPEN on some teacher:
            # that teacher is dead or shedding everything it is offered
            # (the gauge carries the teacher endpoint as a label). The
            # breaker already routed traffic away — this rule is the
            # operator-facing "a teacher needs replacing" signal, so it
            # fires on sustained openings, not a half-open flap.
            "breaker-open", kind="threshold",
            metric="edl_distill_breaker_open",
            op=">=", value=1.0, for_s=10.0, severity="warning",
        ),
        Rule("dead-endpoint", kind="absent", stale_s=30.0, severity="warning"),
        Rule(
            "heartbeat-stale", kind="quantile",
            metric="edl_train_step_heartbeat_age_seconds", q=0.95,
            op=">", value=30.0, window_s=60.0, severity="critical",
        ),
        Rule(
            "restart-detected", kind="restart",
            metric="edl_process_start_time_seconds",
            resolve_s=10.0, severity="info",
        ),
        Rule(
            "telemetry-dropped-keys", kind="rate",
            metric="edl_obs_telemetry_dropped_keys_total",
            op=">", value=0.0, window_s=120.0, severity="warning",
        ),
        Rule(
            # the numerics plane's tripwire: ANY non-finite element in
            # gradients or loss is corruption, never noise — the counter
            # registers at 0 with the first real publish, so the 0 -> N
            # jump is always visible to the rate window
            "nan-detected", kind="rate",
            metric="edl_train_nonfinite_total",
            op=">", value=0.0, window_s=60.0, severity="critical",
        ),
        Rule(
            # windowed z-score of the published loss vs its trailing
            # history: a divergence/corruption spike fires, a healthy
            # monotone descent never does (z stays negative)
            "loss-spike", kind="zscore",
            metric="edl_train_loss",
            op=">", value=4.0, window_s=120.0, severity="critical",
        ),
        Rule(
            # dp replicas publishing different param digests AT THE SAME
            # STEP are not training the same model: a lost broadcast or
            # resharding bug, sustained (one laggy publish is normal)
            "replica-divergence", kind="threshold",
            metric="edl_train_replica_divergence",
            op=">", value=1e-3, for_s=10.0, severity="critical",
        ),
        Rule(
            # the optimizer stopped moving: a gradient norm at zero for
            # a sustained window means dead inputs or a wedged optimizer
            # (the gauge only exists once real steps published, so a
            # compiling job cannot false-fire)
            "grad-stall", kind="threshold",
            metric="edl_train_grad_norm",
            op="<", value=1e-9, for_s=60.0, severity="warning",
        ),
        Rule(
            # the scale plane's flap detector: autoscale-caused drains
            # at a sustained rate mean the controller is thrashing —
            # oscillating world sizes burn restage time the decisions
            # were supposed to buy back. Hysteresis/cooldown in the
            # decision engine should keep this silent; firing is a
            # controller-tuning bug, not weather. require_advance keeps
            # a freshly-registered counter from arming the window.
            "autoscale-thrash", kind="rate",
            metric="edl_launch_drains_total", labels='cause="autoscale"',
            op=">", value=0.05, window_s=120.0, for_s=60.0,
            severity="warning", require_advance=True,
        ),
        Rule(
            # the AOT resize ladder's regression signal: the histogram
            # only gains observations when a cache MISS forces a real
            # XLA compile, so a quiet window is speculation working and
            # a fat p95 after a restage is speculation MISSING (ladder
            # off, portable keys broken, exchange unreachable). Blind
            # windows never fire — a job that never recompiles is the
            # goal state, not a gap.
            "restage-compile-regression", kind="quantile",
            metric="edl_train_restage_compile_seconds", q=0.95,
            op=">", value=5.0, window_s=120.0, severity="warning",
        ),
        Rule(
            # the memory plane's early-warning twin of oom-detected:
            # sustained residency above 92% of the device limit is the
            # regime where one transient (resharding double-buffer, an
            # eval batch) tips into RESOURCE_EXHAUSTED. for_s keeps a
            # harvest-time spike from paging; resolve_s keeps the alert
            # from flapping as the allocator hovers at the line.
            "hbm-pressure", kind="threshold",
            metric="edl_device_hbm_utilization_ratio",
            op=">", value=0.92, for_s=20.0, resolve_s=30.0,
            severity="warning",
        ),
        Rule(
            # an OOM is never weather: the step dispatcher's forensics
            # guard counts each RESOURCE_EXHAUSTED it intercepts, and a
            # single one must page IMMEDIATELY (for_s=0) — the evidence
            # bundle is already on disk, the job is restaging, and the
            # operator owes the plan a smaller world or a bigger margin.
            "oom-detected", kind="rate",
            metric="edl_train_oom_total",
            op=">", value=0.0, window_s=60.0, severity="critical",
        ),
        Rule(
            # donate_argnums silently dropped by XLA (plan shows zero
            # aliased bytes): the state is resident TWICE — peak HBM is
            # a full state-size above what the author believes
            "donation-dropped", kind="rate",
            metric="edl_train_donation_dropped_total",
            op=">", value=0.0, window_s=120.0, severity="warning",
        ),
    ]


def rules_from_json(text: str, base: Optional[List[Rule]] = None) -> List[Rule]:
    """Parse a JSON rule list; with ``base`` given, entries override
    same-named base rules (field-wise) and new names append — so a
    deployment can re-pace one built-in rule without restating the pack."""
    docs = json.loads(text)
    if not isinstance(docs, list):
        raise ValueError("rule file must be a JSON list of rule objects")
    if base is None:
        return [Rule.from_dict(d) for d in docs]
    rules = {r.name: r for r in base}
    order = [r.name for r in base]
    for doc in docs:
        name = doc.get("name")
        if name in rules:
            merged = rules[name].to_dict()
            merged.update(doc)
            rules[name] = Rule.from_dict(merged)
        else:
            rules[name] = Rule.from_dict(doc)
            order.append(name)
    return [rules[n] for n in order]


class _RuleState:
    """Hysteresis + history for one rule."""

    __slots__ = (
        "pending_since", "last_true", "firing", "firing_since",
        "fired_count", "first_fired_ts", "firings", "resolved_ts",
        "seen_advance", "bearers", "start_times", "last_restart_ts",
    )

    def __init__(self) -> None:
        self.pending_since: Optional[float] = None
        self.last_true: Optional[float] = None
        self.firing = False
        self.firing_since: Optional[float] = None
        self.fired_count = 0
        self.first_fired_ts: Optional[float] = None
        self.firings: List[float] = []
        self.resolved_ts: Optional[float] = None
        self.seen_advance = False           # rate rules: require_advance arm
        self.bearers: Dict[str, float] = {}  # rate rules: target -> last ts it bore the series
        self.start_times: Dict[str, float] = {}  # restart rules, per target
        self.last_restart_ts: Optional[float] = None


def _series_sum(
    series: Dict[str, Dict[str, float]], metric: str, label_substr: str
) -> Optional[float]:
    """Sum of every label set of ``metric`` containing ``label_substr``;
    None when the scrape has no matching series at all."""
    found = False
    total = 0.0
    for labels, value in series.get(metric, {}).items():
        if label_substr in labels:
            total += value
            found = True
    return total if found else None


def _latest_value(
    samples: List[Dict], metric: str, label_substr: str
) -> Optional[Tuple[float, float]]:
    """The newest live ``(ts, value)`` of a series in one target's
    window (threshold and restart rules share this scan)."""
    for s in reversed(samples):
        if s["up"]:
            v = _series_sum(s["series"], metric, label_substr)
            if v is not None:
                return s["ts"], v
    return None


class Monitor:
    """Scrape, retain, evaluate, alert — one instance per watched job.

    Headless-friendly: with ``store=None`` the engine runs on samples
    fed through :meth:`ingest` and transitions returned by
    :meth:`evaluate` (the decision-table tests drive it this way);
    with a store it discovers, scrapes and publishes end to end.
    """

    def __init__(
        self,
        store,
        job_id: str,
        rules: Optional[List[Rule]] = None,
        interval: float = 5.0,
        retention_s: float = 300.0,
        monitor_dir: Optional[str] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        scrape_timeout: float = 1.0,
        collect_telemetry: bool = True,
        on_fire: Optional[Callable[[Rule, Dict], None]] = None,
    ) -> None:
        self.job_id = job_id
        self.rules = list(rules) if rules is not None else builtin_rules()
        names = [r.name for r in self.rules]
        if len(names) != len(set(names)):
            raise ValueError("duplicate rule names: %s" % sorted(names))
        self.interval = interval
        self.retention_s = retention_s
        self.scrape_timeout = scrape_timeout
        self.collect_telemetry = collect_telemetry
        # action hooks: each called (rule, alert-record) on every FIRING
        # transition — e.g. obs.profile.AutoCapture requesting an
        # on-device trace of the degraded window, or the scale plane
        # penalizing a degraded job's allocation. A registry, not a
        # single slot: subscribers coexist (add_on_fire) instead of
        # clobbering each other. Exception-contained per hook: one
        # failing action must never stop the sensor OR its peers.
        self._on_fire_hooks: List[Callable[[Rule, Dict], None]] = []
        if on_fire is not None:
            self._on_fire_hooks.append(on_fire)
        self._registry = registry if registry is not None else obs_metrics.default_registry()
        self._m_scrapes = self._registry.counter(
            "edl_monitor_scrapes_total", "scrape attempts, by outcome"
        )
        self._m_alerts = self._registry.counter(
            "edl_monitor_alerts_total", "alert firings, by rule and severity"
        )
        self._m_firing = self._registry.gauge(
            "edl_monitor_rules_firing", "rules currently in the firing state"
        )
        self._m_up = self._registry.gauge(
            "edl_monitor_targets_up", "scrape targets alive at the last sweep"
        )
        self._owns_client = False
        self._client = None
        if store is not None:
            if isinstance(store, str):
                from edl_tpu.store.client import connect_store

                self._client = connect_store(store, timeout=5.0)
                self._owns_client = True
            else:
                self._client = store
        self._lock = threading.Lock()
        self._window: Dict[str, List[Dict]] = {}   # target -> samples
        self._last_up: Dict[str, float] = {}
        self._ever_up: Dict[str, float] = {}       # target -> first-up ts
        self._state: Dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}
        self._complete = False
        self._last_telemetry = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # scrape pool, created once and reused per sweep; _lock-guarded:
        # stop() tears it down on the caller's thread while the sweep
        # thread lazily creates/uses it
        self._pool = None  # edl: guarded-by(self._lock)
        self._series_writer: Optional[obs_events.FlightRecorder] = None
        self._alert_recorder: Optional[obs_events.FlightRecorder] = None
        if monitor_dir:
            self._warm_start(monitor_dir)
            self._series_writer = obs_events.FlightRecorder(
                monitor_dir, component="series", suffix=SERIES_SUFFIX
            )
            self._alert_recorder = obs_events.FlightRecorder(
                monitor_dir, component="monitor"
            )

    # -- firing-action hooks -----------------------------------------------

    @property
    def on_fire(self) -> Optional[Callable[[Rule, Dict], None]]:
        """Back-compat view of the hook registry: the first subscriber
        (or None). Assigning REPLACES the registry — a sole-owner idiom;
        subscribers that must coexist use :meth:`add_on_fire`."""
        return self._on_fire_hooks[0] if self._on_fire_hooks else None

    @on_fire.setter
    def on_fire(self, fn: Optional[Callable[[Rule, Dict], None]]) -> None:
        self._on_fire_hooks = [fn] if fn is not None else []

    def add_on_fire(
        self, fn: Callable[[Rule, Dict], None]
    ) -> Callable[[Rule, Dict], None]:
        """Subscribe a firing-action hook; returns ``fn`` so callers can
        keep the handle for :meth:`remove_on_fire`."""
        self._on_fire_hooks.append(fn)
        return fn

    def remove_on_fire(self, fn: Callable[[Rule, Dict], None]) -> None:
        """Unsubscribe a hook; absent hooks are ignored."""
        try:
            self._on_fire_hooks.remove(fn)
        except ValueError:
            pass

    # -- retention ---------------------------------------------------------

    def _warm_start(self, monitor_dir: str) -> None:
        """Reload the retained window from the ring segments a previous
        monitor incarnation left behind: a restarted monitor resumes its
        rate/staleness windows instead of going blind (rule hysteresis
        state itself restarts pending — firing again is the safe side)."""
        horizon = time.time() - self.retention_s
        warmed = 0
        for doc in obs_events.read_segments(monitor_dir, suffix=SERIES_SUFFIX):
            if doc.get("event") != "sample" or doc.get("ts", 0.0) < horizon:
                continue
            self.ingest(
                str(doc.get("target", "?")),
                doc.get("series") or {},
                up=bool(doc.get("up")),
                ts=float(doc["ts"]),
                _persist=False,
            )
            warmed += 1
        if warmed:
            logger.info("monitor warm-started with %d retained samples", warmed)

    def ingest(
        self,
        target: str,
        series: Dict[str, Dict[str, float]],
        up: bool = True,
        ts: Optional[float] = None,
        _persist: bool = True,
    ) -> None:
        """Retain one observation of one target (``series`` in the
        ``fetch_metrics`` shape; ``up=False`` records a failed probe)."""
        now = ts if ts is not None else time.time()
        sample = {"ts": now, "up": up, "series": series}
        with self._lock:
            window = self._window.setdefault(target, [])
            window.append(sample)
            horizon = now - self.retention_s
            while window and window[0]["ts"] < horizon:
                window.pop(0)
            if up:
                self._last_up[target] = max(self._last_up.get(target, 0.0), now)
                self._ever_up.setdefault(target, now)
        if _persist and self._series_writer is not None:
            self._series_writer.record(
                "sample", target=target, up=up, series=series
            )

    # -- evaluation --------------------------------------------------------

    def _window_for(
        self, rule: Rule, now: float
    ) -> Dict[str, List[Dict]]:
        horizon = now - rule.window_s
        with self._lock:
            return {
                t: [s for s in w if s["ts"] >= horizon]
                for t, w in self._window.items()
                if rule.target in t
            }

    def _eval_threshold(
        self, rule: Rule, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        worst: Optional[float] = None
        evidence: List[Dict] = []
        for target, samples in self._window_for(rule, now).items():
            latest = _latest_value(samples, rule.metric, rule.labels)
            if latest is None:
                continue
            ts, v = latest
            if _OPS[rule.op](v, rule.value):
                evidence.append({"target": target, "value": v, "ts": ts})
            if worst is None or _OPS[rule.op](v, worst):
                worst = v
        return bool(evidence), worst, evidence

    def _eval_rate(
        self, rule: Rule, state: _RuleState, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        windows = self._window_for(rule, now)
        up_ts = [
            s["ts"] for w in windows.values() for s in w if s["up"]
        ]
        if len(up_ts) < 2:
            return False, None, []  # blind window: never alert on no data
        span = max(up_ts) - min(up_ts)
        if span < 0.5 * rule.window_s:
            return False, None, []  # a window still filling proves nothing
        increase = 0.0
        advancing: List[Dict] = []
        for target, samples in windows.items():
            seen = [
                (s["ts"], v) for s in samples if s["up"]
                for v in (_series_sum(s["series"], rule.metric, rule.labels),)
                if v is not None
            ]
            if not seen:
                continue
            state.bearers[target] = max(state.bearers.get(target, 0.0), seen[-1][0])
            first, last = seen[0][1], seen[-1][1]
            # a counter that went BACKWARDS restarted: its new value is
            # the whole post-restart increase
            inc = last - first if last >= first else last
            if inc > 0:
                increase += inc
                advancing.append({"target": target, "value": inc, "ts": seen[-1][0]})
        if increase > 0:
            state.seen_advance = True
        if rule.require_advance and not state.seen_advance:
            return False, None, []
        rate = increase / span if span > 0 else 0.0
        if rule.op in ("<", "<="):
            # a too-LOW rate indicts the series' RECENT bearers that went
            # flat or silent, not whoever still advanced — and a target
            # that stopped bearing long ago (a downsized worker from
            # hours back) is history, not a culprit
            moved = {e["target"] for e in advancing}
            horizon = now - max(10.0 * rule.window_s, 60.0)
            evidence = [
                {"target": t, "value": 0.0, "ts": now}
                for t in sorted(state.bearers)
                if t not in moved and state.bearers[t] >= horizon
            ]
        else:
            evidence = advancing
        return _OPS[rule.op](rate, rule.value), rate, evidence

    def _eval_quantile(
        self, rule: Rule, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        bucket = rule.metric + "_bucket"
        agg: Dict[float, float] = {}
        evidence: List[Dict] = []
        for target, samples in self._window_for(rule, now).items():
            grids = [
                (s["ts"], obs_metrics.bucket_grid(s["series"][bucket], rule.labels))
                for s in samples
                if s["up"] and bucket in s["series"]
            ]
            if len(grids) < 2:
                continue
            first, last = grids[0][1], grids[-1][1]
            added = 0.0
            for le, cum in last.items():
                delta = max(0.0, cum - first.get(le, 0.0))
                agg[le] = agg.get(le, 0.0) + delta
                if le == float("inf"):
                    added = delta
            if added > 0:
                evidence.append({"target": target, "value": added, "ts": grids[-1][0]})
        qv = obs_metrics.quantile_from_grid(agg, rule.q)
        if qv is None:
            return False, None, []  # no new observations: nothing to judge
        return _OPS[rule.op](qv, rule.value), qv, evidence

    def _eval_absent(
        self, rule: Rule, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        evidence: List[Dict] = []
        worst = 0.0
        forget_after = rule.forget_s or 20.0 * rule.stale_s
        with self._lock:
            targets = {
                t: self._last_up[t]
                for t in self._ever_up
                if rule.target in t and t != SELF_TARGET
            }
        for target, last_up in targets.items():
            silent = now - last_up
            if silent > forget_after:
                # obs registrations are permanent keys, so a legitimate
                # permanent departure (downsize, graceful drain) would
                # otherwise page for the rest of the job: after 20x the
                # stale bound the target is RETIRED — the alert stood
                # long enough to be seen, and a comeback on the same key
                # re-registers as up on the next sweep
                with self._lock:
                    self._ever_up.pop(target, None)
                    self._last_up.pop(target, None)
                continue
            if silent > rule.stale_s:
                evidence.append({"target": target, "value": silent, "ts": last_up})
                worst = max(worst, silent)
        return bool(evidence), (worst if evidence else None), evidence

    def _eval_restart(
        self, rule: Rule, state: _RuleState, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        evidence: List[Dict] = []
        for target, samples in self._window_for(rule, now).items():
            latest = _latest_value(samples, rule.metric, rule.labels)
            if latest is None:
                continue
            prev = state.start_times.get(target)
            state.start_times[target] = latest[1]
            if prev is not None and abs(latest[1] - prev) > 1.0:
                state.last_restart_ts = now
                evidence.append(
                    {"target": target, "value": latest[1] - prev, "ts": latest[0]}
                )
        # a restart is an event: condition holds for resolve_s after the
        # last observed jump, then the alert resolves itself
        hold = max(rule.resolve_s, 2 * self.interval)
        cond = (
            state.last_restart_ts is not None
            and now - state.last_restart_ts <= hold
        )
        return cond, (evidence[0]["value"] if evidence else None), evidence

    def _eval_zscore(
        self, rule: Rule, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        worst: Optional[float] = None
        evidence: List[Dict] = []
        for target, samples in self._window_for(rule, now).items():
            seen = [
                (s["ts"], v) for s in samples if s["up"]
                for v in (_series_sum(s["series"], rule.metric, rule.labels),)
                if v is not None
            ]
            # a throttled gauge re-scraped between publishes repeats its
            # value; keeping the duplicates would shrink the trailing std
            # toward zero and make ordinary drift look like a spike
            dedup: List[Tuple[float, float]] = []
            for ts, v in seen:
                if not dedup or v != dedup[-1][1]:
                    dedup.append((ts, v))
            if len(dedup) < _ZSCORE_MIN_POINTS + 1:
                continue  # blind/flat window: nothing to judge
            ts, newest = dedup[-1]
            hist = [v for _, v in dedup[:-1] if math.isfinite(v)]
            if len(hist) < _ZSCORE_MIN_POINTS:
                continue
            mean = sum(hist) / len(hist)
            var = sum((v - mean) ** 2 for v in hist) / len(hist)
            # std floor: a near-constant history (converged loss) must
            # not turn ordinary jitter into an unbounded z
            std = max(math.sqrt(var), 0.05 * abs(mean), 1e-12)
            # a non-finite newest value is an unbounded spike; 1e30, not
            # inf, keeps the published alert record strict-JSON-safe
            z = (newest - mean) / std if math.isfinite(newest) else 1e30
            if _OPS[rule.op](z, rule.value):
                evidence.append({"target": target, "value": z, "ts": ts})
            if worst is None or _OPS[rule.op](z, worst):
                worst = z
        return bool(evidence), worst, evidence

    def _evaluate_rule(
        self, rule: Rule, state: _RuleState, now: float
    ) -> Tuple[bool, Optional[float], List[Dict]]:
        if rule.kind == "threshold":
            return self._eval_threshold(rule, now)
        if rule.kind == "rate":
            return self._eval_rate(rule, state, now)
        if rule.kind == "quantile":
            return self._eval_quantile(rule, now)
        if rule.kind == "absent":
            return self._eval_absent(rule, now)
        if rule.kind == "zscore":
            return self._eval_zscore(rule, now)
        return self._eval_restart(rule, state, now)

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """One evaluation pass over every rule; returns the transitions
        (the published alert records) this pass produced."""
        now = time.time() if now is None else now
        transitions: List[Dict] = []
        for rule in self.rules:
            state = self._state[rule.name]
            try:
                cond, value, evidence = self._evaluate_rule(rule, state, now)
            except Exception as exc:  # noqa: BLE001 — one bad rule must not stop the plane
                logger.warning("rule %s evaluation failed: %s", rule.name, exc)
                continue
            if self._complete:
                # a COMPLETE job is done, not degraded: suppress firing
                # and resolve anything still open
                cond = False
            if cond:
                state.last_true = now
                if state.pending_since is None:
                    state.pending_since = now
                if not state.firing and now - state.pending_since >= rule.for_s:
                    transitions.append(
                        self._transition(rule, state, now, "firing", value, evidence)
                    )
            else:
                state.pending_since = None
                if state.firing and (
                    state.last_true is None
                    or now - state.last_true >= rule.resolve_s
                ):
                    transitions.append(
                        self._transition(rule, state, now, "resolved", value, evidence)
                    )
        self._m_firing.set(sum(1 for s in self._state.values() if s.firing))
        return transitions

    def _transition(
        self,
        rule: Rule,
        state: _RuleState,
        now: float,
        to: str,
        value: Optional[float],
        evidence: List[Dict],
    ) -> Dict:
        if to == "firing":
            state.firing = True
            state.firing_since = now
            state.fired_count += 1
            if state.first_fired_ts is None:
                state.first_fired_ts = now
            state.firings.append(now)
            del state.firings[:-_FIRINGS_KEPT]
            self._m_alerts.inc(rule=rule.name, severity=rule.severity)
            logger.warning(
                "ALERT %s [%s] firing: value=%s targets=%s",
                rule.name, rule.severity, value,
                [e.get("target") for e in evidence[:4]],
            )
        else:
            state.firing = False
            state.resolved_ts = now
            logger.info("alert %s resolved", rule.name)
        doc = {
            "rule": rule.name,
            "severity": rule.severity,
            "state": to,
            "ts": now,
            "since": state.firing_since,
            "resolved_ts": state.resolved_ts,
            "value": value,
            "fired_count": state.fired_count,
            "first_fired_ts": state.first_fired_ts,
            "firings": list(state.firings),
            "evidence": evidence[:8],
            "job_complete": self._complete,
        }
        self._publish(rule, doc)
        if to == "firing":
            for hook in list(self._on_fire_hooks):
                try:
                    hook(rule, doc)
                except Exception as exc:  # noqa: BLE001 — actions must not stop the sensor or each other
                    logger.warning(
                        "on_fire action for %s failed: %s", rule.name, exc
                    )
        rec = self._alert_recorder
        fields = dict(
            rule=rule.name, state=to, severity=rule.severity,
            value=value, fired_count=state.fired_count,
        )
        if rec is not None:
            rec.record("alert", fsync=True, **fields)
        else:
            obs_events.record("alert", fsync=True, **fields)
        return doc

    def _publish(self, rule: Rule, doc: Dict) -> None:
        if self._client is None:
            return
        key = alerts_prefix(self.job_id) + rule.name
        try:  # fire-and-forget, like every telemetry writer
            self._client.put(key, json.dumps(doc).encode())
        except Exception as exc:  # noqa: BLE001
            logger.warning("alert %s not published: %s", rule.name, exc)

    # -- the scrape loop ---------------------------------------------------

    def _check_complete(self) -> None:
        if self._complete or self._client is None:
            return
        try:
            value = self._client.get("/%s/job/status" % self.job_id)
        except Exception:  # noqa: BLE001 — store mid-blip: keep last verdict
            return
        if value == b"COMPLETE":
            self._complete = True
            logger.info("job %s COMPLETE: alert evaluation suppressed", self.job_id)

    def poll_once(self) -> List[Dict]:
        """One full sweep: discover, scrape, retain, evaluate. Returns
        the alert transitions the sweep produced."""
        self._check_complete()
        targets: Dict[str, Dict] = {}
        if self._client is not None:
            targets = obs_http.discover_endpoints(self._client, self.job_id)

        def _probe(item):
            name, info = item
            endpoint = info.get("endpoint", "")
            try:
                series = obs_http.fetch_metrics(
                    endpoint, timeout=self.scrape_timeout
                )
                return name, True, series
            except Exception:  # noqa: BLE001 — dead endpoints are data too
                return name, False, {}

        items = sorted(targets.items())
        results = []
        if items:
            with self._lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    # one long-lived pool: spawning a fresh executor per
                    # sweep is thread churn the watched job would feel
                    self._pool = ThreadPoolExecutor(
                        max_workers=8,
                        thread_name_prefix="edl-monitor-scrape",
                    )
                pool = self._pool
            # map() outside the lock: a sweep must not hold _lock for
            # eight concurrent scrape round-trips
            results = list(pool.map(_probe, items))
        up_count = 0
        for name, up, series in results:
            self._m_scrapes.inc(outcome="ok" if up else "error")
            up_count += 1 if up else 0
            self.ingest(name, series, up=up)
        self._m_up.set(up_count)
        if (
            self._client is not None
            and self.collect_telemetry
            and time.time() - self._last_telemetry >= max(self.interval, 1.0)
        ):
            # throttled to >= 1s: collect() is three keyspace range scans,
            # and a fast-scraping monitor must not double the store load
            # of the job it watches
            self._last_telemetry = time.time()
            try:
                from edl_tpu.utils import telemetry

                telemetry.collect(self._client, self.job_id)
            except Exception:  # noqa: BLE001 — store mid-fault
                pass
        # the monitor's own registry rides the same path as a scraped
        # endpoint: its edl_monitor_* series (and the scraper-side
        # telemetry drop counter) become rule-visible retained samples
        self.ingest(
            SELF_TARGET, obs_http.parse_metrics_text(self._registry.render())
        )
        return self.evaluate()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — the watcher must outlive faults
                logger.warning("monitor sweep failed: %s", exc)

    def start(self) -> "Monitor":
        self._thread = threading.Thread(
            target=self._loop, name="edl-monitord", daemon=True
        )
        self._thread.start()
        logger.info(
            "monitor watching job %s: %d rules, %.2gs interval",
            self.job_id, len(self.rules), self.interval,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if self._series_writer is not None:
            self._series_writer.close()
        if self._alert_recorder is not None:
            self._alert_recorder.close()
        if self._owns_client and self._client is not None:
            self._client.close()
            self._client = None

    # -- introspection -----------------------------------------------------

    @property
    def client(self):
        """The store client this monitor watches through (None when
        headless) — for callers that piggyback on it, e.g. the daemon
        registering its own obs endpoint."""
        return self._client

    def firing(self) -> List[str]:
        return sorted(n for n, s in self._state.items() if s.firing)

    def health(self) -> Dict:
        with self._lock:
            retained = sum(len(w) for w in self._window.values())
            targets = len(self._window)
        return {
            "job": self.job_id,
            "rules": len(self.rules),
            "firing": self.firing(),
            "targets": targets,
            "retained_samples": retained,
            "job_complete": self._complete,
        }
