"""Span tracer: bounded in-process timeline, Chrome-trace-event export.

Replaces the stderr-only ``_RealTimeline`` one-shot profiler
(``edl_tpu/utils/timeline.py``, now a shim over this) with a real
tracing plane:

- ``span()`` is a context manager over ``time.monotonic()`` (wall-clock
  NTP steps can't produce negative or bogus durations);
- completed spans land in a ring buffer (``maxlen`` bounded — tracing a
  million-step job costs a fixed few MB, never OOM);
- export is Chrome trace-event JSON (``chrome://tracing`` / Perfetto).
  Timestamps are mapped back to unix-epoch microseconds through a
  (wall, monotonic) anchor captured at tracer creation, so traces from
  DIFFERENT processes of one job line up on one absolute timeline and
  :mod:`edl_tpu.obs.merge` can splice them without clock negotiation.

Env contract:

    EDL_TRACE_DIR        when set, the process tracer auto-exports to
                         ``{dir}/{component}-{pid}.trace.json`` at exit,
                         every ``EDL_TRACE_INTERVAL`` seconds (default
                         10; atomic replace), and on demand via
                         ``export()``. The periodic export is what makes
                         SIGTERM-killed workers — the NORMAL end of every
                         non-final elastic stage — leave their spans
                         behind: atexit never runs under the default
                         SIGTERM disposition.

The per-process tracer is a lazy singleton (``get_tracer()``); library
code records into it unconditionally — recording is a deque append, and
the buffer bound makes "always on" safe.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

DEFAULT_MAXLEN = 16384


class _SpanHandle:
    """Context manager minted by :meth:`SpanTracer.span`."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Dict) -> None:
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_SpanHandle":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.args = dict(self.args, error=exc_type.__name__)
        self._tracer.record(
            self.name, self._t0, time.monotonic() - self._t0, **self.args
        )


class SpanTracer:
    """Ring-buffer span recorder for ONE process.

    ``component`` names the process in merged traces (store, launcher,
    worker-0, teacher, ...). All public methods are thread-safe.
    """

    def __init__(
        self,
        component: str = "",
        maxlen: int = DEFAULT_MAXLEN,
        pid: Optional[int] = None,
    ) -> None:
        self.component = component or "proc"
        self.pid = os.getpid() if pid is None else pid
        # (wall, monotonic) anchor: event ts = anchor_wall + (mono - anchor_mono)
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()
        self._events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> _SpanHandle:
        """``with tracer.span("train_step", step=i): ...``"""
        return _SpanHandle(self, name, args)

    def record(self, name: str, t0_mono: float, dur_s: float, **args) -> None:
        """Record a completed span (monotonic start + duration seconds)."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": self._to_epoch_us(t0_mono),
            "dur": max(0.0, dur_s) * 1e6,
            "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, ts_wall: Optional[float] = None, **args) -> None:
        """Zero-duration marker (drain triggered, stage published, ...).

        ``ts_wall`` back-dates the marker to a known unix timestamp —
        lazily-flushed events (WorkerMeter's first_step after a slow
        store connect) must land at the time they HAPPENED, or the
        merged trace's downtime decomposition is off by the flush delay.
        """
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",
            "ts": ts_wall * 1e6 if ts_wall is not None
            else self._to_epoch_us(time.monotonic()),
            "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def _to_epoch_us(self, mono: float) -> float:
        return (self._anchor_wall + (mono - self._anchor_mono)) * 1e6

    # -- export ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_events(self) -> List[dict]:
        """Snapshot as Chrome trace events, process metadata included."""
        with self._lock:
            events = list(self._events)
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "args": {"name": self.component},
            }
        ]
        return meta + events

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write ``{"traceEvents": [...]}`` JSON; returns the path.

        Default path needs ``EDL_TRACE_DIR``; without it (and without an
        explicit ``path``) export is a no-op returning None — tracing
        must never error a process that didn't opt in.
        """
        if path is None:
            trace_dir = os.environ.get("EDL_TRACE_DIR")
            if not trace_dir:
                return None
            path = os.path.join(
                trace_dir, "%s-%d.trace.json" % (self.component, self.pid)
            )
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = "%s.tmp.%d" % (path, self.pid)
            with open(tmp, "w") as f:
                # default=str: one numpy scalar passed as a span arg must
                # not poison every future export of the process
                json.dump(
                    {"traceEvents": self.to_events(), "displayTimeUnit": "ms"},
                    f,
                    default=str,
                )
                # postmortems read these after crashes: the atomic rename
                # below only persists the name without a preceding fsync
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — tracing never errors its host
            return None


_tracer: Optional[SpanTracer] = None
_tracer_lock = threading.Lock()


def get_tracer(component: Optional[str] = None) -> SpanTracer:
    """The process tracer (lazy singleton).

    The first caller names the process (later ``component`` args only
    fill in a still-default name); when ``EDL_TRACE_DIR`` is set an
    atexit export hook is registered so every instrumented process
    leaves its timeline behind without explicit teardown.
    """
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            name = component or _default_component()
            _tracer = SpanTracer(component=name)
            if os.environ.get("EDL_TRACE_DIR"):
                atexit.register(_tracer.export)
                _start_periodic_export(_tracer)
        elif component and _tracer.component == "proc":
            _tracer.component = component
        return _tracer


def _start_periodic_export(tracer: SpanTracer) -> None:
    """Flush the ring buffer to disk on a timer: elastic workers die by
    SIGTERM at every resize, which skips atexit — the periodic file
    (atomically replaced) is the trace they leave behind."""
    try:
        interval = float(os.environ.get("EDL_TRACE_INTERVAL", "10"))
    except ValueError:
        interval = 10.0
    if interval <= 0:
        return

    def _loop() -> None:
        while True:
            time.sleep(interval)
            tracer.export()

    threading.Thread(
        target=_loop, name="edl-trace-export", daemon=True
    ).start()


def _default_component() -> str:
    comp = os.environ.get("EDL_OBS_COMPONENT")
    if comp:
        return comp
    if os.environ.get("EDL_WORKER_RANK") is not None and os.environ.get(
        "EDL_JOB_ID"
    ):
        return "worker-%s" % os.environ.get("EDL_WORKER_RANK")
    return "proc"


def span(name: str, **args) -> _SpanHandle:
    """Record a span into the process tracer."""
    return get_tracer().span(name, **args)
