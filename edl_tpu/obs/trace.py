"""Span tracer: bounded in-process timeline, Chrome-trace-event export.

Replaces the stderr-only ``_RealTimeline`` one-shot profiler
(``edl_tpu/utils/timeline.py``, now a shim over this) with a real
tracing plane:

- ``span()`` is a context manager over ``time.monotonic()`` (wall-clock
  NTP steps can't produce negative or bogus durations);
- completed spans land in a ring buffer (``maxlen`` bounded — tracing a
  million-step job costs a fixed few MB, never OOM);
- export is Chrome trace-event JSON (``chrome://tracing`` / Perfetto).
  Timestamps are mapped back to unix-epoch microseconds through a
  (wall, monotonic) anchor captured at tracer creation, so traces from
  DIFFERENT processes of one job line up on one absolute timeline and
  :mod:`edl_tpu.obs.merge` can splice them without clock negotiation.

Env contract:

    EDL_TRACE_DIR        when set, the process tracer auto-exports to
                         ``{dir}/{component}-{pid}.trace.json`` at exit,
                         every ``EDL_TRACE_INTERVAL`` seconds (default
                         10; atomic replace), and on demand via
                         ``export()``. The periodic export is what makes
                         SIGTERM-killed workers — the NORMAL end of every
                         non-final elastic stage — leave their spans
                         behind: atexit never runs under the default
                         SIGTERM disposition.
    EDL_TRACE_PROPAGATE  distributed-tracing master switch: "1" forces
                         wire-level trace-context propagation on, "0"
                         forces it off; unset, propagation follows
                         ``EDL_TRACE_DIR`` (a job that exports traces
                         wants them stitched). Disarmed, every call site
                         pays ONE attribute load per frame — the same
                         discipline as the chaos fault points.

The per-process tracer is a lazy singleton (``get_tracer()``); library
code records into it unconditionally — recording is a deque append, and
the buffer bound makes "always on" safe.

Distributed causal tracing (DESIGN.md "Distributed tracing"): spans can
carry Dapper-style ``trace_id``/``span_id``/``parent_id`` linkage in
their args. Context lives in a contextvar (request-scoped spans: one
store RPC, one predict) layered over a process-wide *operation* context
(the restage/drain window a worker lives in from spawn to first step).
Clients inject the current context as a ``"tc"`` field in EDL1 request
payloads; servers adopt it so their handler spans become children of
the caller's span — see :func:`child_span` and
:func:`edl_tpu.rpc.wire.server_span`. Job-level operations (restage,
drain) derive their trace id DETERMINISTICALLY from a key every
participant already shares (the stage token, the pod id), so the drain
trigger in one launcher, the publish in another, and the restore in a
freshly spawned worker all stitch into one trace with zero extra wire
traffic — ``tools/edl_trace.py`` extracts the cross-process critical
path from the merged exports.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, NamedTuple, Optional

DEFAULT_MAXLEN = 16384


# -- distributed trace context ------------------------------------------------


class TraceContext(NamedTuple):
    """One node of a distributed trace: ``span_id`` is the node, and any
    span recorded UNDER this context parents to it."""

    trace_id: str
    span_id: str

    def wire(self) -> List[str]:
        """The ``"tc"`` request-payload field (EDL1 convention)."""
        return [self.trace_id, self.span_id]


def context_from_wire(tc) -> Optional["TraceContext"]:
    """Parse a ``"tc"`` payload field; None on anything malformed — a
    hostile or torn field must degrade to an unlinked span, never error
    the server's dispatch loop."""
    if not isinstance(tc, (list, tuple)) or len(tc) < 2:
        return None
    try:
        trace_id, span_id = tc[0], tc[1]
        if isinstance(trace_id, bytes):
            trace_id = trace_id.decode()
        if isinstance(span_id, bytes):
            span_id = span_id.decode()
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        if not trace_id or not span_id or len(trace_id) > 64 or len(span_id) > 64:
            return None
        return TraceContext(trace_id, span_id)
    except (TypeError, IndexError, KeyError, UnicodeDecodeError):
        return None


class _Propagation:
    """Arming state for wire-level context propagation.

    ``armed`` is a plain bool attribute so the disarmed cost at every
    call site is one attribute load per frame — the same discipline as
    the chaos fault points and the bound counters in rpc/wire.py.
    """

    __slots__ = ("armed",)

    def __init__(self) -> None:
        self.armed = self._from_env()

    @staticmethod
    def _from_env() -> bool:
        flag = os.environ.get("EDL_TRACE_PROPAGATE", "").strip()
        if flag:
            return flag != "0"
        return bool(os.environ.get("EDL_TRACE_DIR"))

    def rearm(self) -> bool:
        """Re-read the env (tests, and processes that set EDL_TRACE_DIR
        after import)."""
        self.armed = self._from_env()
        return self.armed


PROPAGATION = _Propagation()

# request-scoped context (one RPC, one predict): contextvar so server
# handler threads and nested client calls stay correctly scoped
_ctx: "contextvars.ContextVar[Optional[TraceContext]]" = contextvars.ContextVar(
    "edl_trace_ctx", default=None
)
# process-wide operation context (the restage/drain window this process
# currently lives in): plain module state so EVERY thread — checkpoint
# restore, cache pull, reconnect loops — inherits it without contextvar
# plumbing. Written only by begin/end_process_op.
_op_ctx: Optional[TraceContext] = None


def _span_id() -> str:
    return os.urandom(8).hex()


def op_trace_id(op: str, key: str) -> str:
    """Deterministic trace id for a job-level operation: every process
    that knows ``(op, key)`` — e.g. ("restage", stage_token) — computes
    the same id, so cross-process segments stitch with no negotiation."""
    return hashlib.sha256(("edl:%s:%s" % (op, key)).encode()).hexdigest()[:16]  # edl: blocking-ok(one sha256 over a <64-byte key at operation roots: microseconds, rarer than a lease sweep)


def op_root_id(trace_id: str) -> str:
    """Deterministic span id of an operation's root anchor: segments can
    parent to the root before (or without) ever seeing it recorded."""
    return hashlib.sha256(("root:%s" % trace_id).encode()).hexdigest()[:16]  # edl: blocking-ok(one sha256 over a 16-byte trace id: microseconds, rarer than a lease sweep)


def op_context(op: str, key: str) -> TraceContext:
    tid = op_trace_id(op, key)
    return TraceContext(tid, op_root_id(tid))


def current() -> Optional[TraceContext]:
    """The effective context: an explicit span scope wins, else the
    process's operation window, else None."""
    ctx = _ctx.get()
    return ctx if ctx is not None else _op_ctx


def current_trace_id() -> Optional[str]:
    ctx = current()
    return ctx.trace_id if ctx is not None else None


def inject() -> Optional[List[str]]:
    """The ``"tc"`` field for an outgoing request, or None. Call sites
    guard with ``PROPAGATION.armed`` first so the disarmed hot path pays
    one attribute load, not a function call."""
    ctx = current()
    return ctx.wire() if ctx is not None else None


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]):
    """Make ``ctx`` current for the block WITHOUT recording a span (e.g.
    so a flight record inherits an operation's trace id)."""
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


@contextlib.contextmanager
def child_span(name: str, tc: Optional[TraceContext] = None, **args):
    """Record ``name`` as a child span of ``tc`` (or the current
    context); within the block the new span is the current context, so
    nested spans and injected requests parent to it. With no parent at
    all, the span roots a fresh trace."""
    parent = tc if tc is not None else current()
    if parent is not None:
        ctx = TraceContext(parent.trace_id, _span_id())
        args = dict(args, parent_id=parent.span_id)
    else:
        ctx = TraceContext(_span_id() + _span_id(), _span_id())
    args["trace_id"] = ctx.trace_id
    args["span_id"] = ctx.span_id
    token = _ctx.set(ctx)
    t0 = time.monotonic()
    try:
        yield ctx
    except Exception as exc:
        args["error"] = type(exc).__name__
        raise
    finally:
        _ctx.reset(token)
        get_tracer().record(name, t0, time.monotonic() - t0, **args)


@contextlib.contextmanager
def op_segment(name: str, op: str, key: str, **args):
    """One segment of a deterministic operation trace: a child span of
    the (possibly not-yet-recorded) op root. For processes that touch an
    operation without living inside it — the leader publishing a stage,
    a peer spawning workers."""
    with child_span(name, tc=op_context(op, key), op=op, **args) as ctx:
        yield ctx


def record_op_root(op: str, key: str, **args) -> TraceContext:
    """Record the operation's root anchor span (zero duration — the op's
    extent is its segments') with the deterministic ids; returns the
    root context. Exactly one process should call this per op instance
    (the CAS winner, the promoted standby); everyone else records
    segments that parent to the derived root id."""
    ctx = op_context(op, key)
    get_tracer().record(
        "op:%s" % op, time.monotonic(), 0.0,
        op=op, op_key=key, root=True,
        trace_id=ctx.trace_id, span_id=ctx.span_id, **args,
    )
    return ctx


def begin_process_op(op: str, key: str, **args) -> Optional[TraceContext]:
    """Enter a process-wide operation window (a worker's restage from
    spawn/init to first step, a drain from notice to exit): until
    :func:`end_process_op`, every span recorded without a more specific
    context — and every flight-recorder record — carries this trace.
    Re-entering the SAME op+key is a no-op (init() runs twice)."""
    global _op_ctx
    ctx = op_context(op, key)
    if _op_ctx is not None and _op_ctx.trace_id == ctx.trace_id:
        return _op_ctx
    _op_ctx = ctx
    if args and PROPAGATION.armed:
        get_tracer().instant("op_enter:%s" % op, **args)
    return ctx


def end_process_op() -> None:
    """Leave the process operation window. Callers record their closing
    segment (``first_step``) BEFORE ending the window, so auto-linkage
    (see :meth:`SpanTracer.record`) stitches it into the op trace."""
    global _op_ctx
    _op_ctx = None


def reset_context() -> None:
    """Drop every live context (tests)."""
    global _op_ctx
    _op_ctx = None
    _ctx.set(None)


class _SpanHandle:
    """Context manager minted by :meth:`SpanTracer.span`."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Dict) -> None:
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_SpanHandle":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.args = dict(self.args, error=exc_type.__name__)
        self._tracer.record(
            self.name, self._t0, time.monotonic() - self._t0, **self.args
        )


class SpanTracer:
    """Ring-buffer span recorder for ONE process.

    ``component`` names the process in merged traces (store, launcher,
    worker-0, teacher, ...). All public methods are thread-safe.
    """

    def __init__(
        self,
        component: str = "",
        maxlen: int = DEFAULT_MAXLEN,
        pid: Optional[int] = None,
    ) -> None:
        self.component = component or "proc"
        self.pid = os.getpid() if pid is None else pid
        # (wall, monotonic) anchor: event ts = anchor_wall + (mono - anchor_mono)
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()
        self._events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> _SpanHandle:
        """``with tracer.span("train_step", step=i): ...``"""
        return _SpanHandle(self, name, args)

    def record(self, name: str, t0_mono: float, dur_s: float, **args) -> None:
        """Record a completed span (monotonic start + duration seconds).

        With propagation armed and a live trace context (a request scope
        or the process's operation window), spans that do not already
        carry linkage become CHILDREN of it automatically — this is how
        pre-existing instrumentation (ckpt_restore, spawn_workers,
        train_step) stitches into restage traces without per-site edits.
        """
        if PROPAGATION.armed and "trace_id" not in args:
            ctx = current()
            if ctx is not None:
                args = dict(
                    args, trace_id=ctx.trace_id, span_id=_span_id(),
                    parent_id=ctx.span_id,
                )
        ev = {
            "name": name,
            "ph": "X",
            "ts": self._to_epoch_us(t0_mono),
            "dur": max(0.0, dur_s) * 1e6,
            "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, ts_wall: Optional[float] = None, **args) -> None:
        """Zero-duration marker (drain triggered, stage published, ...).

        ``ts_wall`` back-dates the marker to a known unix timestamp —
        lazily-flushed events (WorkerMeter's first_step after a slow
        store connect) must land at the time they HAPPENED, or the
        merged trace's downtime decomposition is off by the flush delay.
        """
        if PROPAGATION.armed and "trace_id" not in args:
            ctx = current()
            if ctx is not None:
                args = dict(args, trace_id=ctx.trace_id, parent_id=ctx.span_id)
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",
            "ts": ts_wall * 1e6 if ts_wall is not None
            else self._to_epoch_us(time.monotonic()),
            "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def _to_epoch_us(self, mono: float) -> float:
        return (self._anchor_wall + (mono - self._anchor_mono)) * 1e6

    # -- export ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_events(self) -> List[dict]:
        """Snapshot as Chrome trace events, process metadata included."""
        with self._lock:
            events = list(self._events)
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "args": {"name": self.component},
            }
        ]
        return meta + events

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write ``{"traceEvents": [...]}`` JSON; returns the path.

        Default path needs ``EDL_TRACE_DIR``; without it (and without an
        explicit ``path``) export is a no-op returning None — tracing
        must never error a process that didn't opt in.
        """
        if path is None:
            trace_dir = os.environ.get("EDL_TRACE_DIR")
            if not trace_dir:
                return None
            path = os.path.join(
                trace_dir, "%s-%d.trace.json" % (self.component, self.pid)
            )
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = "%s.tmp.%d" % (path, self.pid)
            with open(tmp, "w") as f:
                # default=str: one numpy scalar passed as a span arg must
                # not poison every future export of the process
                json.dump(
                    {"traceEvents": self.to_events(), "displayTimeUnit": "ms"},
                    f,
                    default=str,
                )
                # postmortems read these after crashes: the atomic rename
                # below only persists the name without a preceding fsync
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — tracing never errors its host
            return None


_tracer: Optional[SpanTracer] = None
_tracer_lock = threading.Lock()


def get_tracer(component: Optional[str] = None) -> SpanTracer:
    """The process tracer (lazy singleton).

    The first caller names the process (later ``component`` args only
    fill in a still-default name); when ``EDL_TRACE_DIR`` is set an
    atexit export hook is registered so every instrumented process
    leaves its timeline behind without explicit teardown.
    """
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            name = component or _default_component()
            _tracer = SpanTracer(component=name)
            if os.environ.get("EDL_TRACE_DIR"):
                atexit.register(_tracer.export)
                _start_periodic_export(_tracer)
        elif component and _tracer.component == "proc":
            _tracer.component = component
        return _tracer


def _start_periodic_export(tracer: SpanTracer) -> None:
    """Flush the ring buffer to disk on a timer: elastic workers die by
    SIGTERM at every resize, which skips atexit — the periodic file
    (atomically replaced) is the trace they leave behind."""
    try:
        interval = float(os.environ.get("EDL_TRACE_INTERVAL", "10"))
    except ValueError:
        interval = 10.0
    if interval <= 0:
        return

    def _loop() -> None:
        while True:
            time.sleep(interval)
            tracer.export()

    threading.Thread(
        target=_loop, name="edl-trace-export", daemon=True
    ).start()


def _default_component() -> str:
    comp = os.environ.get("EDL_OBS_COMPONENT")
    if comp:
        return comp
    if os.environ.get("EDL_WORKER_RANK") is not None and os.environ.get(
        "EDL_JOB_ID"
    ):
        return "worker-%s" % os.environ.get("EDL_WORKER_RANK")
    return "proc"


def span(name: str, **args) -> _SpanHandle:
    """Record a span into the process tracer."""
    return get_tracer().span(name, **args)
