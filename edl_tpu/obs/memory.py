"""HBM memory observability plane: plans, watermarks, census, forensics.

BENCH_r04 put the workload at 92.5% of its memory roofline, yet the only
memory signal in the stack was the coarse ``edl_device_hbm_bytes_in_use``
gauge pair — nobody could say which buffers own HBM, whether a resize
target *fits*, or what was resident when an OOM killed a pod. This
module is the decomposition (Williams et al.'s roofline methodology
needs one) and the feasibility model (Pollux-style schedulers reassign
resources; without a per-configuration memory model they happily choose
allocations the device cannot hold). Four legs:

**(a) Compile-time memory plans.** XLA already computed the step's exact
memory footprint at compile time — ``Compiled.memory_analysis()`` breaks
it into argument / output / temp / alias / generated-code bytes. The
plan is harvested at every jit seam (the live stage in train/loop.py;
each AOT ladder rung in train/aot.py, whose neighbor-world executables
are compiled anyway, so their plans are free), exported as
``edl_train_hbm_plan_bytes{kind=...}`` gauges, published to the store
under ``mem/plan/{world}`` (:data:`MEM_SERVICE`), and scored against the
runtime high-water mark (``edl_train_hbm_plan_accuracy_pct``).

**(b) Fit-gated elasticity.** :func:`fit_check` / :func:`read_plans` are
the feasibility model the scale plane (scale/decide.py, scale/scaler.py)
and the launcher's reconcile path consult: a target world whose
published plan exceeds the device limit minus the ``EDL_MEM_MARGIN``
safety fraction is refused or walked down, and the store records the
decision with cause ``mem_unfit``.

**(c) Runtime census & watermarks.** Per-stage resettable peak tracking
from ``device.memory_stats()`` (peak/reserved fields when the backend
has them; on CPU backends the live-buffer byte total stands in), plus a
throttled top-K live-buffer census via ``jax.live_arrays()`` — metadata
only (shape/dtype/nbytes), flight-recorded like the numerics probe, and
NEVER a host sync on the step path — and a fragmentation estimate
(reserved-but-unused fraction of the reservation).

**(d) OOM forensics.** :meth:`MemoryPlane.oom_guard` wraps step dispatch:
a RESOURCE_EXHAUSTED error triggers a crash-safe forensics bundle
(device memory profile capture, an unthrottled census, the active plan,
an fsync'd ``oom`` flight instant) BEFORE the error propagates into the
drain/restage machinery. The monitor rules ``hbm-pressure`` and
``oom-detected`` (obs/monitor.py) and the ``hbm-oom`` chaos scenario
close the loop.

Everything is best-effort telemetry: no method raises into training, and
a backend without ``memory_analysis``/``memory_stats`` degrades to
whichever legs still have data.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.log import get_logger

logger = get_logger("obs.memory")

__all__ = [
    "MEM_SERVICE",
    "PLAN_KINDS",
    "MemoryPlan",
    "MemoryPlane",
    "census",
    "fit_check",
    "fit_cap",
    "harvest_plan",
    "is_oom",
    "mem_margin",
    "census_every",
    "publish_plan",
    "read_plans",
]

# store keyspace (see cluster/contract.py layout docs):
# mem/plan/{world} -> json MemoryPlan doc for the train step compiled at
#   ``world`` processes — written by whichever process compiled it (the
#   live stage or an AOT ladder rung), permanent, last-writer-wins. The
#   scale plane and the launcher's reconcile path read the whole service
#   to fit-gate resize targets.
MEM_SERVICE = "mem"
PLAN_KEY_FMT = "plan/%d"

# memory_analysis() legs, in CompiledMemoryStats attribute order
PLAN_KINDS = ("argument", "output", "temp", "alias", "generated_code")

# top-K buffers the census keeps per pass: enough to name the owners of
# HBM without turning the flight record into a full heap dump
CENSUS_TOP_K = 8


def mem_margin() -> float:
    """``EDL_MEM_MARGIN``: fraction of the device limit held back as
    safety headroom by every fit check (fragmentation, allocator slack,
    collectives scratch XLA does not plan). Single read site."""
    try:
        return float(os.environ.get("EDL_MEM_MARGIN", "0.08"))
    except ValueError:
        return 0.08


def census_every() -> int:
    """``EDL_MEM_CENSUS_EVERY``: steps between live-buffer census passes
    (0 disables the census entirely). Single read site."""
    try:
        return int(os.environ.get("EDL_MEM_CENSUS_EVERY", "200"))
    except ValueError:
        return 200


# -- (a) compile-time memory plans --------------------------------------------


class MemoryPlan:
    """One executable's compile-time memory footprint, by kind (bytes).

    ``limit`` is the publishing device's capacity (bytes_limit) stamped
    at harvest time, so a deviceless reader — the scaler, the launcher's
    reconcile path — can fit-check the plan without ever seeing the
    device (0 = unknown, which always fits: the gate refuses only on
    positive evidence)."""

    __slots__ = ("argument", "output", "temp", "alias", "generated_code",
                 "world", "ts", "limit")

    def __init__(
        self,
        argument: float = 0.0,
        output: float = 0.0,
        temp: float = 0.0,
        alias: float = 0.0,
        generated_code: float = 0.0,
        world: int = 0,
        ts: float = 0.0,
        limit: float = 0.0,
    ) -> None:
        self.argument = float(argument)
        self.output = float(output)
        self.temp = float(temp)
        self.alias = float(alias)
        self.generated_code = float(generated_code)
        self.world = int(world)
        self.ts = float(ts)
        self.limit = float(limit)

    def total(self) -> float:
        """Planned peak residency: arguments + outputs + temps + code.
        Aliased (donated) bytes are NOT double-counted — they live
        inside the argument figure and are the part the output reuses."""
        return (
            self.argument + self.output + self.temp + self.generated_code
            - min(self.alias, self.output)
        )

    def by_kind(self) -> Dict[str, float]:
        return {k: getattr(self, k) for k in PLAN_KINDS}

    def to_doc(self) -> Dict[str, Any]:
        doc = self.by_kind()
        doc["total"] = self.total()
        doc["world"] = self.world
        doc["ts"] = self.ts
        doc["limit"] = self.limit
        return doc

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "MemoryPlan":
        return cls(
            **{k: float(doc.get(k, 0.0)) for k in PLAN_KINDS},
            world=int(doc.get("world", 0)),
            ts=float(doc.get("ts", 0.0)),
            limit=float(doc.get("limit", 0.0)),
        )

    @classmethod
    def from_compiled(
        cls, compiled, world: int = 0
    ) -> Optional["MemoryPlan"]:
        """Harvest ``Compiled.memory_analysis()`` — None when the
        backend/jax version has no analysis (never raises)."""
        try:
            ma = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — analysis is telemetry, not a dependency
            return None
        if ma is None:
            return None
        get = lambda attr: float(getattr(ma, attr + "_size_in_bytes", 0.0) or 0.0)  # noqa: E731
        return cls(
            argument=get("argument"),
            output=get("output"),
            temp=get("temp"),
            alias=get("alias"),
            generated_code=get("generated_code"),
            world=world,
            ts=time.time(),
        )


def harvest_plan(step_fn, *args, world: int = 0, **kwargs) -> Optional[MemoryPlan]:
    """The memory plan for one call of a jitted ``step_fn`` at the given
    arguments — ``lower().compile()`` rides the jit/persistent cache
    (the executable already exists for a step that has run), so this is
    a jax trace plus a cache hit, like ``obs_profile.step_cost``.
    Accepts an already-``Compiled`` object directly. Returns None on any
    failure: the plan is telemetry, never a correctness dependency."""
    try:
        if hasattr(step_fn, "memory_analysis"):
            return MemoryPlan.from_compiled(step_fn, world=world)
        compiled = step_fn.lower(*args, **kwargs).compile()
        return MemoryPlan.from_compiled(compiled, world=world)
    except Exception as exc:  # noqa: BLE001 — backend/API drift degrades to no plan
        logger.debug("memory plan extraction failed: %s", exc)
        return None


def publish_plan(client, job_id: str, plan: MemoryPlan) -> bool:
    """Publish ``plan`` under ``mem/plan/{world}`` (permanent,
    last-writer-wins — a recompile at the same world supersedes).
    Best-effort: False on store trouble, never raises."""
    if client is None or not job_id or plan.world <= 0:
        return False
    try:
        from edl_tpu.discovery.registry import Registry

        Registry(client, job_id).set_permanent(
            MEM_SERVICE,
            PLAN_KEY_FMT % plan.world,
            json.dumps(plan.to_doc()).encode(),
        )
        return True
    except Exception as exc:  # noqa: BLE001 — store blip: next harvest retries
        logger.debug("mem plan publish failed: %s", exc)
        return False


def read_plans(client, job_id: str) -> Dict[int, MemoryPlan]:
    """Every published ``mem/plan/{world}`` doc, keyed by world.
    Best-effort: {} on store trouble (an absent plan must read as
    "unknown", never as "unfit")."""
    if client is None or not job_id:
        return {}
    try:
        from edl_tpu.discovery.registry import Registry

        metas = Registry(client, job_id).get_service(MEM_SERVICE)
    except Exception:  # noqa: BLE001 — store blip: fit gate sees no plans
        return {}
    out: Dict[int, MemoryPlan] = {}
    for meta in metas:
        name = getattr(meta, "name", "")
        if not name.startswith("plan/"):
            continue
        try:
            world = int(name[len("plan/"):])
            out[world] = MemoryPlan.from_doc(json.loads(meta.value))
        except (ValueError, TypeError):
            continue
    return out


# -- (b) fit checks ------------------------------------------------------------


def fit_check(
    plan_total: float, limit: float, margin: Optional[float] = None
) -> bool:
    """Does a plan of ``plan_total`` bytes fit a device of ``limit``
    bytes, after holding back the safety ``margin`` fraction? A
    non-positive limit means "unknown capacity" and always fits — the
    gate refuses only on positive evidence."""
    if limit <= 0 or plan_total <= 0:
        return True
    m = mem_margin() if margin is None else margin
    return plan_total <= limit * (1.0 - m)


def fit_cap(
    plans: Dict[int, MemoryPlan],
    limit: float = 0.0,
    margin: Optional[float] = None,
) -> Optional[int]:
    """The largest published world that still fits (None when no plan
    with a usable limit is published — unknown never caps; 0 when every
    known plan is over-limit). ``limit`` overrides the per-plan device
    limit stamped at harvest time; left at 0, each plan is checked
    against its own embedded limit."""
    fitting: List[int] = []
    judged = False
    for w, p in plans.items():
        lim = limit if limit > 0 else p.limit
        if lim <= 0 or p.total() <= 0:
            continue  # no verdict possible for this world
        judged = True
        if fit_check(p.total(), lim, margin):
            fitting.append(w)
    if not judged:
        return None
    return max(fitting) if fitting else 0


# -- (c) runtime census --------------------------------------------------------


def census(top_k: int = CENSUS_TOP_K) -> Dict[str, Any]:
    """One live-buffer census pass: every ``jax.live_arrays()`` entry's
    shape/dtype/nbytes — METADATA only, no device sync, no value reads
    (a donated buffer that died between listing and inspection is
    skipped). Returns ``{buffers, bytes, top: [{shape, dtype, nbytes,
    count}...]}`` with the top-K aggregated by (shape, dtype)."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — no backend: empty census
        return {"buffers": 0, "bytes": 0.0, "top": []}
    total = 0.0
    count = 0
    groups: Dict[tuple, List[float]] = {}
    for arr in arrays:
        try:
            nbytes = float(arr.nbytes)
            key = (str(tuple(arr.shape)), str(arr.dtype))
        except Exception:  # noqa: BLE001 — deleted mid-walk: not resident, skip
            continue
        total += nbytes
        count += 1
        groups.setdefault(key, []).append(nbytes)
    top = sorted(
        (
            {"shape": shape, "dtype": dtype,
             "nbytes": sum(sizes), "count": len(sizes)}
            for (shape, dtype), sizes in groups.items()
        ),
        key=lambda g: -g["nbytes"],
    )[:top_k]
    return {"buffers": count, "bytes": total, "top": top}


def is_oom(exc: BaseException) -> bool:
    """Is this the allocator saying no? XLA surfaces device OOM as a
    RESOURCE_EXHAUSTED ``XlaRuntimeError`` (message text is the stable
    part across jaxlib versions; the class moved modules twice)."""
    text = "%s: %s" % (type(exc).__name__, exc)
    return (
        "RESOURCE_EXHAUSTED" in text
        or "Out of memory" in text
        or "out of memory" in text
    )


class _OomGuard:
    """Context manager half of :meth:`MemoryPlane.oom_guard`."""

    __slots__ = ("_plane", "_ctx")

    def __init__(self, plane: "MemoryPlane", ctx: Dict[str, Any]) -> None:
        self._plane = plane
        self._ctx = ctx

    def __enter__(self) -> "_OomGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and is_oom(exc):
            self._plane.forensics(exc, **self._ctx)
        return False  # always propagate: drain/restage owns recovery


class MemoryPlane:
    """One stage's memory observability: plans, watermarks, census, OOM.

    Created per training stage (train/loop.py, chaos/trainee.py) next to
    ``StepTelemetry``; :meth:`close` releases the gauge bindings so a
    restaged stage never leaves the old stage's closures in the
    process-global registry. Every public method is best-effort and
    None-safe: the plane observes training, it never gates it.
    """

    def __init__(
        self,
        device=None,
        stage: str = "",
        rank: int = 0,
        client=None,
        job_id: str = "",
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        expect_donation: bool = False,
    ) -> None:
        self._reg = (
            registry if registry is not None else obs_metrics.default_registry()
        )
        if device is None:
            try:
                import jax

                device = jax.local_devices()[0]
            except Exception:  # noqa: BLE001 — no backend: stats legs stay dark
                device = None
        self._device = device
        self.stage = stage
        self.rank = rank
        self._client = client
        self._job_id = job_id
        self._expect_donation = expect_donation
        self._lock = threading.Lock()
        self.plan: Optional[MemoryPlan] = None
        self._census_interval = census_every()
        self._last_census_step: Optional[int] = None
        # stage-local watermark: peak of whatever residency signal this
        # backend has (bytes_in_use, else the census byte total)
        self._peak = 0.0
        self._in_use = 0.0
        self._limit = 0.0
        self._reserved = 0.0
        self._frag = 0.0
        self._census_bytes = 0.0
        self._census_buffers = 0.0
        self._m_oom = self._reg.counter(
            "edl_train_oom_total",
            "RESOURCE_EXHAUSTED errors caught at step dispatch (forensics "
            "bundle captured for each)",
        )
        self._m_donation = self._reg.counter(
            "edl_train_donation_dropped_total",
            "steps compiled with donate_argnums whose memory plan shows "
            "zero aliased bytes — XLA silently dropped the donation",
        )
        self._m_census = self._reg.counter(
            "edl_mem_census_passes_total",
            "live-buffer census passes completed by the memory plane",
        )
        self._m_plan = self._reg.gauge(
            "edl_train_hbm_plan_bytes",
            "compile-time memory plan for the live train step, by kind "
            "(memory_analysis: argument/output/temp/alias/generated_code)",
        )
        self._binding = obs_metrics.bind_gauges(
            [
                (
                    "edl_device_hbm_peak_bytes",
                    "stage-local high-water mark of device memory in use "
                    "(reset on stage start; census-derived on backends "
                    "without memory_stats)",
                    lambda: self._peak,
                ),
                (
                    "edl_device_hbm_reserved_bytes",
                    "allocator bytes reserved from the device (0 when the "
                    "backend does not report reservations)",
                    lambda: self._reserved,
                ),
                (
                    "edl_device_hbm_utilization_ratio",
                    "device memory in use over its limit (the hbm-pressure "
                    "rule's signal; 0 when the backend reports no limit)",
                    self._utilization,
                ),
                (
                    "edl_device_hbm_fragmentation_ratio",
                    "reserved-but-unused fraction of the allocator's "
                    "reservation — a fragmentation/slack estimate",
                    lambda: self._frag,
                ),
                (
                    "edl_mem_census_live_bytes",
                    "total bytes of live jax arrays at the last census pass",
                    lambda: self._census_bytes,
                ),
                (
                    "edl_mem_census_live_buffers",
                    "live jax array count at the last census pass",
                    lambda: self._census_buffers,
                ),
            ],
            self._reg,
        )

    # -- plans -------------------------------------------------------------

    def harvest(self, step_fn, *args, world: int = 0, **kwargs) -> Optional[MemoryPlan]:
        """Harvest the live step's plan (see :func:`harvest_plan`), export
        the per-kind gauges, run the donation cross-check, publish to the
        store, and leave an fsync'd ``mem_plan`` flight record."""
        plan = harvest_plan(step_fn, *args, world=world, **kwargs)
        if plan is None:
            return None
        self._sample_stats()
        with self._lock:
            plan.limit = self._limit
            self.plan = plan
        for kind, v in plan.by_kind().items():
            self._m_plan.set(v, kind=kind)
        self._m_plan.set(plan.total(), kind="total")
        if self._expect_donation and plan.alias <= 0 and plan.argument > 0:
            # the step was built with donate_argnums but XLA's plan shows
            # no aliased bytes: the donation was silently dropped (layout
            # mismatch, copy inserted) — the state is resident TWICE
            self._m_donation.inc()
            obs_events.record(
                "donation_dropped", fsync=True, component="memory",
                stage=self.stage, rank=self.rank, world=world,
                argument_bytes=plan.argument,
            )
            logger.warning(
                "memory plan for world=%d shows donate_argnums had no "
                "effect (alias bytes == 0; state resident twice)", world,
            )
        publish_plan(self._client, self._job_id, plan)
        obs_events.record(
            "mem_plan", fsync=True, component="memory", stage=self.stage,
            rank=self.rank, world=world,
            total_bytes=plan.total(), temp_bytes=plan.temp,
            alias_bytes=plan.alias,
        )
        return plan

    def harvest_rung(self, compiled, world: int) -> Optional[MemoryPlan]:
        """Harvest an AOT ladder rung's plan from its already-compiled
        executable (the compile happened for the resize ladder — the
        plan is free) and publish it under ``mem/plan/{world}``. Does
        NOT touch the live-stage plan or its gauges."""
        plan = MemoryPlan.from_compiled(compiled, world=world)
        if plan is None:
            return None
        with self._lock:
            plan.limit = self._limit
        publish_plan(self._client, self._job_id, plan)
        obs_events.record(
            "mem_plan", fsync=True, component="memory", stage=self.stage,
            rank=self.rank, world=world, rung=True,
            total_bytes=plan.total(), temp_bytes=plan.temp,
            alias_bytes=plan.alias,
        )
        return plan

    # -- runtime sampling --------------------------------------------------

    def _utilization(self) -> float:
        if self._limit <= 0:
            return 0.0
        return self._in_use / self._limit

    def _sample_stats(self) -> None:
        """Refresh in_use/limit/peak/reserved from the device, updating
        the stage watermark. Cheap host call, no device sync."""
        from edl_tpu.obs import profile as obs_profile

        stats = (
            obs_profile.device_memory_stats_full(self._device)
            if self._device is not None else None
        )
        with self._lock:
            if stats:
                self._in_use = stats.get("bytes_in_use", 0.0)
                self._limit = stats.get("bytes_limit", 0.0)
                self._reserved = stats.get("bytes_reserved", 0.0)
                peak = max(
                    self._in_use, stats.get("peak_bytes_in_use", 0.0)
                )
                if self._reserved > 0:
                    self._frag = max(
                        0.0, (self._reserved - self._in_use) / self._reserved
                    )
            else:
                # CPU/debug backends: the census byte total is the only
                # residency signal — the watermark tracks it instead
                peak = self._census_bytes
            self._peak = max(self._peak, peak)

    def on_step(self, step_idx: int) -> None:
        """Per-step hook: throttled stats sample + census. Never syncs
        the device, never raises. Off the census cadence this is one
        modulo and a return."""
        every = self._census_interval
        if every <= 0:
            return
        if (
            self._last_census_step is not None
            and step_idx - self._last_census_step < every
        ):
            return
        self._last_census_step = step_idx
        try:
            self.run_census(step_idx)
        except Exception as exc:  # noqa: BLE001 — telemetry must not break the step
            logger.debug("mem census failed at step %d: %s", step_idx, exc)

    def run_census(self, step_idx: int = -1, fsync: bool = False) -> Dict[str, Any]:
        """One unthrottled census + stats sample; flight-records the
        result (fsync'd only when forensics asks — routine passes ride
        the segment buffer like every chatty marker)."""
        snap = census()
        with self._lock:
            self._census_bytes = float(snap["bytes"])
            self._census_buffers = float(snap["buffers"])
        self._sample_stats()
        self._m_census.inc()
        obs_events.record(
            "mem_census", fsync=fsync, component="memory", stage=self.stage,
            rank=self.rank, step=step_idx,
            live_bytes=snap["bytes"], live_buffers=snap["buffers"],
            top=snap["top"],
        )
        return snap

    def reset_peak(self) -> None:
        """Per-stage watermark reset (stage start / after a restage)."""
        with self._lock:
            self._peak = 0.0

    def watermark(self) -> float:
        with self._lock:
            return self._peak

    def plan_accuracy(self) -> Optional[float]:
        """Plan-vs-actual score: min/max ratio of the planned total and
        the stage watermark, as a percentage (100 = XLA's plan matched
        the runtime high-water mark exactly). None until both exist."""
        with self._lock:
            plan, peak = self.plan, self._peak
        if plan is None or peak <= 0:
            return None
        planned = plan.total()
        if planned <= 0:
            return None
        acc = 100.0 * min(planned, peak) / max(planned, peak)
        self._reg.gauge(
            "edl_train_hbm_plan_accuracy_pct",
            "plan-vs-actual: min/max ratio of the compile-time plan total "
            "and the stage's runtime high-water mark, in percent",
        ).set(acc)
        return acc

    # -- (d) OOM forensics -------------------------------------------------

    def oom_guard(self, **ctx) -> _OomGuard:
        """Wrap step dispatch: ``with plane.oom_guard(step=n): step(...)``.
        A RESOURCE_EXHAUSTED error triggers :meth:`forensics` and then
        propagates unchanged into the drain/restage machinery."""
        return _OomGuard(self, ctx)

    def forensics(self, exc: BaseException, **ctx) -> Optional[str]:
        """Crash-safe OOM evidence, captured while the heap that OOMed is
        still resident: an unthrottled census, the device memory profile
        (when jax.profiler has one), the active plan, and an fsync'd
        ``oom`` flight instant — then a durable JSON bundle. Returns the
        bundle path (None when no flight dir is configured)."""
        self._m_oom.inc()
        try:
            snap = self.run_census(fsync=True)
        except Exception:  # noqa: BLE001 — forensics on a dying process: best effort
            snap = {"buffers": 0, "bytes": 0.0, "top": []}
        flight_dir = os.environ.get(obs_events.ENV_DIR)
        bundle_path = None
        profile_path = None
        if flight_dir:
            try:
                os.makedirs(flight_dir, exist_ok=True)
                stamp = "%d.%d" % (int(time.time() * 1000), os.getpid())
                profile_path = os.path.join(
                    flight_dir, "oom-%s.memprof" % stamp
                )
                try:
                    import jax

                    jax.profiler.save_device_memory_profile(profile_path)
                except Exception:  # noqa: BLE001 — profile capture is optional evidence
                    profile_path = None
                bundle_path = os.path.join(flight_dir, "oom-%s.json" % stamp)
                bundle = {
                    "ts": time.time(),
                    "stage": self.stage,
                    "rank": self.rank,
                    "error": "%s: %s" % (type(exc).__name__, exc),
                    "plan": self.plan.to_doc() if self.plan else None,
                    "census": snap,
                    "peak_bytes": self.watermark(),
                    "in_use_bytes": self._in_use,
                    "limit_bytes": self._limit,
                    "memory_profile": profile_path,
                    "ctx": {k: str(v) for k, v in ctx.items()},
                }
                tmp = bundle_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(bundle, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, bundle_path)
            except OSError as io_exc:
                logger.warning("oom bundle write failed: %s", io_exc)
                bundle_path = None
        obs_events.record(
            "oom", fsync=True, component="memory", stage=self.stage,
            rank=self.rank, error=str(exc)[:300], bundle=bundle_path or "",
            live_bytes=snap["bytes"], live_buffers=snap["buffers"],
            peak_bytes=self.watermark(),
            **{k: str(v) for k, v in ctx.items()},
        )
        logger.error(
            "OOM at stage=%s rank=%d: %s (forensics: %s)",
            self.stage, self.rank, str(exc)[:200], bundle_path or "flight only",
        )
        return bundle_path

    def close(self) -> None:
        """Score the stage (plan accuracy) and release the gauge
        closures — a restaged stage must not pin this one alive."""
        try:
            self.plan_accuracy()
        except Exception:  # noqa: BLE001 — closing telemetry never raises
            pass
        self._binding.release()
