"""Regression sentinel: declarative per-metric gates over archived runs.

The run archive (:mod:`edl_tpu.obs.archive`) turns every chaos
scenario, bench, and harness job into an indexed row of scalar rollups;
this module is the judgment half: a declarative per-metric table in the
monitor-:class:`~edl_tpu.obs.monitor.Rule` style — each
:class:`Metric` names a rollup, the direction that counts as better, a
relative tolerance, and the minimum baseline sample count — evaluated
against a **rolling baseline** of the last K archived runs sharing the
same ``(kind, backend, world)`` key. The verdicts are
``regressed`` / ``improved`` / ``ok`` / ``insufficient-baseline``;
``tools/edl_report.py --check`` exits nonzero on any ``regressed``,
which is the whole PR gate.

Baseline hygiene: rows flagged ``excluded`` (e.g. BENCH_r05's honest
0.0 — a measurement that refused to invent a number), ``stale`` (a
cached result from an older sha), or with failed invariants
(``ok == False``) never enter a baseline and are never themselves
judged — a red chaos run must not poison the bar for the next green
one.

Env contract:

    EDL_REPORT_BASELINE_K   rolling-baseline window (default 5 runs)
    EDL_REPORT_TOLERANCES   per-metric tolerance overrides, e.g.
                            ``restage_s=0.5,mfu=0.02`` (relative
                            fractions, same unit as ``Metric.tolerance``)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

from edl_tpu.utils.log import get_logger

logger = get_logger("obs.regress")

_DIRECTIONS = ("lower", "higher")

VERDICT_REGRESSED = "regressed"
VERDICT_IMPROVED = "improved"
VERDICT_OK = "ok"
VERDICT_INSUFFICIENT = "insufficient-baseline"


@dataclasses.dataclass
class Metric:
    """One row of the regression table (the monitor-Rule idiom).

    ``floor`` is an absolute no-page band for metrics whose SLO is a
    bar, not a ratio: a lower-is-better value at or below ``floor``
    (at or above, for higher-is-better) is unconditionally within SLO
    and never judged relatively — ``per_chip_loss_pct`` hovers around
    zero, where relative deltas explode, but the north-star contract is
    simply "<= 5"."""

    name: str                 # rollup key in the index rows
    direction: str = "lower"  # which way is BETTER: "lower" | "higher"
    tolerance: float = 0.25   # relative slack vs the baseline median
    min_samples: int = 1      # baseline rows required before judging
    floor: Optional[float] = None  # absolute always-ok band (SLO bar)
    severity: str = "warning"

    def __post_init__(self) -> None:
        if self.direction not in _DIRECTIONS:
            raise ValueError(
                "metric %r: unknown direction %r (have: %s)"
                % (self.name, self.direction, ", ".join(_DIRECTIONS))
            )
        if self.tolerance < 0:
            raise ValueError(
                "metric %r: negative tolerance %r" % (self.name, self.tolerance)
            )

    def within_floor(self, value: float) -> bool:
        if self.floor is None:
            return False
        return (
            value <= self.floor
            if self.direction == "lower"
            else value >= self.floor
        )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def builtin_metrics() -> List[Metric]:
    """The built-in regression table. Tolerances are sized for shared
    CPU rigs (single-core serialization noise is real); tighten per
    deployment via ``EDL_REPORT_TOLERANCES`` or a custom table. Every
    name matches a rollup the archive derives (archive.py) or a bench
    headline."""
    return [
        # goodput ledger (chaos scenarios, archived harness jobs)
        Metric("goodput_ratio", "higher", 0.15, severity="critical"),
        Metric("restage_s", "lower", 0.40),
        Metric("down_s", "lower", 0.60),
        Metric("traced_restage_s", "lower", 0.40),
        # resize bench
        Metric("resize_downtime", "lower", 0.40, severity="critical"),
        Metric("restage_compile_s", "lower", 0.60),
        Metric("restage_restore_s", "lower", 0.50),
        # the BASELINE north star is an absolute bar (<= 5%), and the
        # value hovers around zero where relative deltas are meaningless
        Metric("per_chip_loss_pct", "lower", 0.50, floor=5.0),
        # store bench
        Metric("store_puts_per_s", "higher", 0.25, severity="critical"),
        Metric("store_put_p99_ms", "lower", 0.50),
        # store bench --reads (standby read-serving lane): wider than the
        # put lane — read throughput on a 1-CPU rig swings with scheduler
        # interleaving of the reader threads (observed ~26% run-to-run)
        Metric("store_reads_per_s", "higher", 0.35, severity="critical"),
        Metric("store_read_p99_ms", "lower", 0.50),
        # checkpoint bench
        Metric("peer_restore_s", "lower", 0.40),
        Metric("durable_restore_s_raw", "lower", 0.40),
        Metric("push_s", "lower", 0.40),
        Metric("save_s", "lower", 0.40),
        # on-chip headline (bench.py / lm benches)
        Metric("resnet50_vd_train_throughput_tpu", "higher", 0.05,
               severity="critical"),
        Metric("mfu", "higher", 0.05),
        # convergence-vs-churn: extra loss a churned run carries over the
        # calm control at the same step budget. Hovers near zero on the
        # quadratic trainee, so the absolute bar does the real gating.
        Metric("convergence_churn_gap", "lower", 0.50, floor=0.3),
        # numerics probe A/B lane (bench.py --numerics-overhead): the
        # fused bundle must stay within the paper bar. Near-zero values
        # make relative deltas meaningless — the 2% floor is the gate.
        Metric("numerics_probe_overhead_pct", "lower", 0.50, floor=2.0),
        # scale plane (autoscale-churn drill): scheduler quality vs the
        # offline oracle replaying the same trace. The drill's own gate
        # is 65%; the trend floor is the healthy band CPU rigs land in,
        # so only a genuine decision-engine regression pages.
        Metric("autoscale_goodput_loss_pct", "lower", 0.50, floor=35.0,
               severity="critical"),
        # decision fsync -> reconciled restage publish, worst pair of
        # the run (restage cost dominates; relative gating suffices)
        Metric("decision_to_restage_s", "lower", 0.60),
        # serving resilience plane (serve_slo / serve-slo-churn): goodput
        # (in-SLO answers/s) from the nominal lane, the answered-request
        # tail, and the refused fraction. Shed hovers at zero in the
        # nominal lane, so the absolute 5% floor does the gating there.
        Metric("serve_qps", "higher", 0.25, severity="critical"),
        Metric("serve_p99_ms", "lower", 0.60),
        Metric("serve_shed_pct", "lower", 0.50, floor=5.0),
        # memory plane (hbm-oom drill): runtime high-water mark, and how
        # much of it the compile-time plan predicted. Peak creeping UP is
        # the regression (a new resident buffer nobody budgeted); plan
        # accuracy is floor-banded because the CPU rig's census-derived
        # peak counts host-side buffers the XLA plan never models — the
        # toy trainee lands ~27%, so >= 20 is unconditionally in-SLO and
        # only a collapse below the bar (plans stopped tracking reality)
        # is judged at all.
        Metric("hbm_peak_gb", "lower", 0.40),
        Metric("hbm_plan_accuracy_pct", "higher", 0.50, floor=20.0),
    ]


def baseline_k() -> int:
    try:
        return max(1, int(os.environ.get("EDL_REPORT_BASELINE_K", "5")))
    except ValueError:
        return 5


def tolerance_overrides(text: Optional[str] = None) -> Dict[str, float]:
    """Parse ``metric=frac,metric=frac``; unparseable entries are
    dropped with a warning, never fatal."""
    raw = (
        text if text is not None
        else os.environ.get("EDL_REPORT_TOLERANCES", "")
    ).strip()
    out: Dict[str, float] = {}
    if not raw:
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        try:
            out[name.strip()] = float(val)
        except ValueError:
            logger.warning("ignoring malformed tolerance override %r", part)
    return out


def metrics_table(
    overrides: Optional[Dict[str, float]] = None,
    base: Optional[List[Metric]] = None,
) -> List[Metric]:
    metrics = list(base) if base is not None else builtin_metrics()
    overrides = (
        overrides if overrides is not None else tolerance_overrides()
    )
    for m in metrics:
        if m.name in overrides:
            m.tolerance = overrides[m.name]
    return metrics


# -- evaluation ---------------------------------------------------------------


def run_key(row: Dict) -> Tuple[str, str, Optional[int]]:
    """The comparability key: runs are only measured against runs of
    the same kind on the same backend at the same world size."""
    world = row.get("world")
    return (
        str(row.get("kind", "")),
        str(row.get("backend", "")),
        int(world) if isinstance(world, (int, float)) else None,
    )


def usable_baseline(row: Dict) -> bool:
    return (
        not row.get("excluded")
        and not row.get("stale")
        and row.get("ok") is not False
    )


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2.0


def evaluate_run(
    row: Dict,
    prior_rows: List[Dict],
    metrics: Optional[List[Metric]] = None,
    k: Optional[int] = None,
) -> List[Dict]:
    """Judge ONE run against the rolling baseline of its same-key
    predecessors; returns one verdict dict per table metric present in
    the run's rollups."""
    metrics = metrics if metrics is not None else metrics_table()
    k = k if k is not None else baseline_k()
    key = run_key(row)
    base_rows = [
        r for r in prior_rows if run_key(r) == key and usable_baseline(r)
    ][-k:]
    rollups = row.get("rollups") or {}
    verdicts: List[Dict] = []
    for m in metrics:
        value = rollups.get(m.name)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        samples = [
            float((r.get("rollups") or {}).get(m.name))
            for r in base_rows
            if isinstance((r.get("rollups") or {}).get(m.name), (int, float))
            and not isinstance((r.get("rollups") or {}).get(m.name), bool)
        ]
        doc = {
            "metric": m.name,
            "value": float(value),
            "n_baseline": len(samples),
            "direction": m.direction,
            "tolerance_pct": round(m.tolerance * 100, 2),
            "severity": m.severity,
        }
        if m.within_floor(float(value)):
            doc["verdict"] = VERDICT_OK
            doc["floor"] = m.floor
            verdicts.append(doc)
            continue
        if len(samples) < m.min_samples:
            doc["verdict"] = VERDICT_INSUFFICIENT
            verdicts.append(doc)
            continue
        base = _median(samples)
        delta = (float(value) - base) / max(abs(base), 1e-9)
        worse = delta if m.direction == "lower" else -delta
        if worse > m.tolerance:
            verdict = VERDICT_REGRESSED
        elif worse < -m.tolerance:
            verdict = VERDICT_IMPROVED
        else:
            verdict = VERDICT_OK
        doc.update(
            verdict=verdict,
            baseline=round(base, 6),
            delta_pct=round(delta * 100, 2),
        )
        verdicts.append(doc)
    return verdicts


def evaluate_latest(
    rows: List[Dict],
    metrics: Optional[List[Metric]] = None,
    k: Optional[int] = None,
) -> Tuple[List[Dict], bool]:
    """For every ``(kind, backend, world)`` key, judge the NEWEST
    usable run against the rolling baseline of its predecessors.
    Returns ``([{key, bundle, verdicts}, ...], ok)`` — ``ok`` is False
    iff any verdict regressed (``insufficient-baseline`` never gates:
    a first run has nothing to regress against)."""
    metrics = metrics if metrics is not None else metrics_table()
    k = k if k is not None else baseline_k()
    by_key: Dict[Tuple, List[Dict]] = {}
    for row in rows:
        by_key.setdefault(run_key(row), []).append(row)
    out: List[Dict] = []
    for key, krows in sorted(by_key.items(), key=lambda kv: repr(kv[0])):
        # judge the newest usable LIVE run; legacy-import rows are
        # history, never the run under judgment (an --import-legacy run
        # AFTER today's archive must not demote today's run to baseline)
        judged = next(
            (r for r in reversed(krows)
             if usable_baseline(r) and not r.get("legacy")),
            None,
        ) or next((r for r in reversed(krows) if usable_baseline(r)), None)
        if judged is None:
            continue
        judged_at = krows.index(judged)
        # baseline = everything before the judged run, plus legacy rows
        # wherever they landed in the index (chronologically they ARE
        # prior history even when appended after a live run) — legacy
        # first, so the rolling [-k:] window keeps the NEWEST live runs
        prior = [
            r for r in krows if r is not judged and r.get("legacy")
        ] + [
            r for i, r in enumerate(krows)
            if r is not judged and not r.get("legacy") and i < judged_at
        ]
        verdicts = evaluate_run(judged, prior, metrics, k)
        if not verdicts:
            continue
        out.append(
            {
                "key": list(key),
                "bundle": judged.get("bundle") or judged.get("source"),
                "verdicts": verdicts,
            }
        )
    ok = not any(
        v["verdict"] == VERDICT_REGRESSED
        for entry in out
        for v in entry["verdicts"]
    )
    return out, ok
