"""Numpy arrays over the wire protocol.

The reference moves teacher predictions as Paddle-Serving feed/fetch
ndarray maps (python/edl/distill/distill_worker.py:262-291); here arrays
ride the same msgpack frames as everything else, tagged so decode is
unambiguous. Contiguous bytes only — no pickling, so frames are safe to
exchange with the native C++ runtime.
"""

from __future__ import annotations

import numpy as np

_ND_KEY = "__nd__"


def encode_ndarray(arr: np.ndarray) -> dict:
    arr = np.ascontiguousarray(arr)
    return {
        _ND_KEY: True,
        "dtype": arr.dtype.str,
        "shape": list(arr.shape),
        "data": arr.tobytes(),
    }


def decode_ndarray(obj: dict) -> np.ndarray:
    return np.frombuffer(obj["data"], dtype=np.dtype(obj["dtype"])).reshape(
        obj["shape"]
    )


def is_encoded_ndarray(obj) -> bool:
    return isinstance(obj, dict) and obj.get(_ND_KEY) is True


def encode_tree(obj):
    """Recursively encode ndarrays inside dicts/lists/tuples."""
    if isinstance(obj, np.ndarray):
        return encode_ndarray(obj)
    if isinstance(obj, (list, tuple)):
        return [encode_tree(x) for x in obj]
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (np.generic,)):
        return obj.item()
    return obj


def decode_tree(obj):
    if is_encoded_ndarray(obj):
        return decode_ndarray(obj)
    if isinstance(obj, list):
        return [decode_tree(x) for x in obj]
    if isinstance(obj, dict):
        return {k: decode_tree(v) for k, v in obj.items()}
    return obj
