"""Framed-TCP wire protocol shared by all edl_tpu control-plane services.

One frame = an 8-byte header (4-byte magic ``EDL1`` + uint32-LE payload
length) followed by a msgpack-encoded payload. The same framing is spoken by
the Python services and the native C++ runtime (``native/``), so either side
of any control-plane connection can be swapped for its native twin.

This replaces BOTH of the reference's control-plane transports — gRPC/
protobuf services (pod_server.proto, data_server.proto,
distill_discovery.proto) and the hand-rolled epoll JSON protocol with CRC
magic ``\\xCB\\xEF\\x00\\x00`` (python/edl/distill/redis/balance_server.py:
40-216) — with a single codegen-free protocol.

Payload conventions (by example, not schema):
  request:  {"i": <id>, "m": <method>, ...params}
  response: {"i": <id>, "ok": true, ...result}
  error:    {"i": <id>, "ok": false, "err": {"etype": ..., "detail": ...}}
  push:     {"w": <watch_id>, "ev": [...]}          (server-initiated)

Bulk-data frames (``EDL2``) carry raw binary attachments after the msgpack
body — header = magic + uint32 total_len + uint32 body_len. The body
references attachments by offset (see ``edl_tpu.rpc.ndarray`` ndrefs), so
large arrays ride the socket via scatter/gather I/O with no intermediate
copies: the predict path moves teacher batches at memcpy speed instead of
re-buffering them through msgpack. ``EDL1``-only peers (the native C++
master) never see EDL2 — it is used only on array-bearing connections.
"""

from __future__ import annotations

import struct
import time as _time
from typing import Iterator, List, Optional, Sequence, Tuple

import msgpack

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import trace as _obs_trace
from edl_tpu.obs.metrics import counter as _counter
from edl_tpu.obs.metrics import histogram as _histogram

# fault points (edl_tpu/chaos): disarmed cost is one attribute load per
# frame — the same order as the counter incs below
_FP_TX = _fault_point(
    "rpc.wire.tx", "outgoing frame: corrupt header bits, delay, or drop"
)
_FP_RX = _fault_point(
    "rpc.wire.rx", "incoming frame decode: delay or drop (peer looks dead)"
)

# label-resolved children: one dict hit per frame on the hot path
_TX_FRAMES = _counter(
    "edl_rpc_tx_frames_total", "wire frames encoded for send"
).labels()
_TX_BYTES = _counter(
    "edl_rpc_tx_bytes_total", "wire bytes encoded for send (header+body+attachments)"
).labels()
_RX_FRAMES = _counter(
    "edl_rpc_rx_frames_total", "wire frames decoded from the socket"
).labels()
_RX_BYTES = _counter(
    "edl_rpc_rx_bytes_total", "wire bytes decoded from the socket"
).labels()

# distributed tracing (obs/trace.py): requests may carry a "tc" field
# ([trace_id, span_id] of the caller's current span); servers wrap their
# handlers in server_span() so the handling span is a child of it AND
# every wire server exports per-method tail latency. Injection call
# sites guard on _TC.armed — one attribute load per frame disarmed.
_TC = _obs_trace.PROPAGATION
TC_FIELD = "tc"

SERVER_SECONDS = _histogram(
    "edl_rpc_server_seconds",
    "server-side RPC handling time, by method and server "
    "(store/data/distill/cache)",
)

# label-resolved children, keyed (method, server): methods here are
# SERVER-defined (call sites wrap only resolved handlers, never a
# client-supplied unknown method string), so the cache is bounded
_SERVER_BOUND: dict = {}


def _server_bound(method: str, server: str):
    child = _SERVER_BOUND.get((method, server))
    if child is None:
        child = _SERVER_BOUND[(method, server)] = SERVER_SECONDS.labels(
            method=method, server=server
        )
    return child


class _ServerSpan:
    """Context manager timing one server-side RPC dispatch into
    ``edl_rpc_server_seconds{method,server}`` and — when the caller
    propagated a trace context — recording the handling interval as a
    child span of the caller's span. Slot-based, no generator frame:
    this sits on every wire server's per-frame hot path. A malformed
    ``tc`` degrades to an unlinked timing."""

    __slots__ = ("_method", "_tc", "_server", "_t0", "_cm")

    def __init__(self, method: str, tc, server: str) -> None:
        self._method = method
        self._tc = tc
        self._server = server

    def __enter__(self) -> "_ServerSpan":
        self._t0 = _time.monotonic()
        self._cm = None
        if self._tc and _TC.armed:
            ctx = _obs_trace.context_from_wire(self._tc)
            if ctx is not None:
                self._cm = _obs_trace.child_span(
                    "rpc:%s" % self._method, tc=ctx, server=self._server
                )
                self._cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._cm is not None:
            self._cm.__exit__(exc_type, exc, tb)
        _server_bound(self._method, self._server).observe(
            _time.monotonic() - self._t0
        )


def server_span(method: str, tc=None, server: str = "") -> _ServerSpan:
    """See :class:`_ServerSpan`; ``tc`` is the raw ``"tc"`` payload
    field (or None)."""
    return _ServerSpan(method, tc, server)


MAGIC = b"EDL1"
MAGIC2 = b"EDL2"
_HEADER = struct.Struct("<4sI")
_HEADER2 = struct.Struct("<4sII")
HEADER_SIZE = _HEADER.size
HEADER2_SIZE = _HEADER2.size
MAX_FRAME = 512 * 1024 * 1024  # bound a corrupt length field


class WireError(Exception):
    pass


def pack_frame(payload: dict, fault: bool = True) -> bytes:
    """``fault=False`` exempts a call site from the ``rpc.wire.tx`` fault
    point — for frames that never cross a network (the store's WAL
    journal): a "network" fault must not corrupt durable state."""
    body = msgpack.packb(payload, use_bin_type=True)
    _TX_FRAMES.inc()
    _TX_BYTES.inc(HEADER_SIZE + len(body))
    frame = _HEADER.pack(MAGIC, len(body)) + body
    if fault and _FP_TX.armed:
        # corrupt flips the magic: the peer sees a torn frame and closes
        frame = _FP_TX.fire(frame, method=payload.get("m"))
    return frame


def pack_frame_buffers(
    payload: dict, attachments: Sequence[memoryview]
) -> List:
    """EDL2 frame as a buffer list for scatter/gather send — the large
    attachments are NOT copied into the frame."""
    body = msgpack.packb(payload, use_bin_type=True)
    total = len(body) + sum(a.nbytes for a in attachments)
    if total > MAX_FRAME:
        raise WireError("frame length %d exceeds limit" % total)
    _TX_FRAMES.inc()
    _TX_BYTES.inc(HEADER2_SIZE + total)
    header = _HEADER2.pack(MAGIC2, total, len(body))
    return [header, body, *attachments]


def send_buffers(sock, buffers: List) -> None:
    """sendmsg the buffer list, handling partial sends and IOV limits."""
    # drop zero-length views: sendmsg reports 0 bytes for them, which is
    # indistinguishable from no progress
    views = [v for b in buffers if (v := memoryview(b).cast("B")).nbytes]
    while views:
        sent = sock.sendmsg(views[:64])
        while sent:
            if sent >= views[0].nbytes:
                sent -= views[0].nbytes
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def unpack_payload(body: bytes) -> dict:
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class FrameReader:
    """Incremental frame decoder for a nonblocking byte stream.

    Feed it whatever ``recv`` returned; it yields complete decoded payloads
    and buffers the remainder.
    """

    def __init__(self, fault: bool = True) -> None:
        # fault=False exempts non-network readers (WAL replay) from the
        # rpc.wire.rx fault point — see pack_frame
        self._buf = bytearray()
        self._fault = fault

    def feed(self, data: bytes) -> List[dict]:
        if self._fault and _FP_RX.armed:
            _FP_RX.fire(n=len(data))
        self._buf.extend(data)
        out: List[dict] = []
        while True:
            payload = self._try_next()
            if payload is None:
                return out
            out.append(payload)

    def _try_next(self) -> Optional[dict]:
        if len(self._buf) < HEADER_SIZE:
            return None
        magic, length = _HEADER.unpack_from(self._buf, 0)
        if magic == MAGIC2:
            if len(self._buf) < HEADER2_SIZE:
                return None
            _, total, body_len = _HEADER2.unpack_from(self._buf, 0)
            if total > MAX_FRAME or body_len > total:
                raise WireError("bad EDL2 lengths %d/%d" % (body_len, total))
            end = HEADER2_SIZE + total
            if len(self._buf) < end:
                return None
            body = bytes(self._buf[HEADER2_SIZE : HEADER2_SIZE + body_len])
            atts = bytes(self._buf[HEADER2_SIZE + body_len : end])
            del self._buf[:end]
            _RX_FRAMES.inc()
            _RX_BYTES.inc(end)
            from edl_tpu.rpc.ndarray import resolve_ndrefs

            return resolve_ndrefs(unpack_payload(body), memoryview(atts))
        if magic != MAGIC:
            raise WireError("bad frame magic %r" % magic)
        if length > MAX_FRAME:
            raise WireError("frame length %d exceeds limit" % length)
        end = HEADER_SIZE + length
        if len(self._buf) < end:
            return None
        body = bytes(self._buf[HEADER_SIZE:end])
        del self._buf[:end]
        _RX_FRAMES.inc()
        _RX_BYTES.inc(end)
        return unpack_payload(body)


def read_frame_blocking(sock) -> dict:
    """Read exactly one frame (EDL1 or EDL2) from a blocking socket.

    For EDL2 the whole frame lands in ONE buffer and ndarray refs in the
    payload are resolved to zero-copy views over it."""
    if _FP_RX.armed:
        _FP_RX.fire()
    header = _recv_exact(sock, HEADER_SIZE)
    magic, length = _HEADER.unpack(header)
    if magic == MAGIC2:
        extra = _recv_exact(sock, HEADER2_SIZE - HEADER_SIZE)
        total, body_len = length, struct.unpack("<I", extra)[0]
        if total > MAX_FRAME or body_len > total:
            raise WireError("bad EDL2 lengths %d/%d" % (body_len, total))
        buf = bytearray(total)
        _recv_exact_into(sock, memoryview(buf))
        _RX_FRAMES.inc()
        _RX_BYTES.inc(HEADER2_SIZE + total)
        payload = unpack_payload(bytes(buf[:body_len]))
        from edl_tpu.rpc.ndarray import resolve_ndrefs

        # toreadonly: both receive paths hand out immutable views
        return resolve_ndrefs(
            payload, memoryview(buf)[body_len:].toreadonly()
        )
    if magic != MAGIC:
        raise WireError("bad frame magic %r" % magic)
    if length > MAX_FRAME:
        raise WireError("frame length %d exceeds limit" % length)
    body = _recv_exact(sock, length)
    _RX_FRAMES.inc()
    _RX_BYTES.inc(HEADER_SIZE + length)
    return unpack_payload(body)


def request_once(endpoint: str, payload: dict, timeout: float = 1.0) -> dict:
    """One-shot request/response on a fresh blocking connection.

    Dial, send one frame, read one frame, close. Control-plane probes
    (standby promotion checks, epoch fence campaigns) use this so they
    never entangle with a long-lived client's connection state. Raises
    ``OSError``/``WireError`` on any failure — callers treat the peer as
    unreachable."""
    import socket as _socket

    from edl_tpu.utils.net import split_endpoint

    if _TC.armed and TC_FIELD not in payload:
        tc = _obs_trace.inject()
        if tc is not None:
            payload = dict(payload, tc=tc)
    with _socket.create_connection(split_endpoint(endpoint), timeout=timeout) as sock:
        sock.settimeout(timeout)
        sock.sendall(pack_frame(payload))
        return read_frame_blocking(sock)


def read_entries_capped(
    names: Sequence[str],
    path_for,
    cap: int,
) -> Tuple[dict, List[str], int]:
    """Byte-capped bulk file read for entry-serving RPCs (the PR-8
    cache-exchange transfer discipline, shared with the checkpoint
    replica plane): returns ``(entries, truncated, sent_bytes)``.

    ``path_for(name)`` maps a (caller-validated) entry name to a local
    path, or returns None to refuse it. The response frame is bounded by
    ``cap`` bytes of entry payload — TPU-sized entries (step executables,
    checkpoint shards) can individually run tens-to-hundreds of MB, and
    a handful in one frame would blow ``MAX_FRAME``, dropping the small
    entries riding the same chunk too. Stat before read so a pushed-out
    entry costs nothing; always ship at least one entry so the caller
    makes progress; names pushed out are returned in ``truncated`` for
    the caller to re-request."""
    import os as _os

    entries: dict = {}
    truncated: List[str] = []
    sent = 0
    for name in names:
        path = path_for(name)
        if path is None:
            continue
        try:
            if entries and sent + _os.path.getsize(path) > cap:
                truncated.append(name)
                continue
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            continue
        if entries and sent + len(data) > cap:
            truncated.append(name)  # grew between stat and read
            continue
        entries[name] = data
        sent += len(data)
    return entries, truncated, sent


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def _recv_exact_into(sock, view: memoryview) -> None:
    while view.nbytes:
        got = sock.recv_into(view)
        if not got:
            raise ConnectionError("peer closed during frame read")
        view = view[got:]
