"""Framed-TCP wire protocol shared by all edl_tpu control-plane services.

One frame = an 8-byte header (4-byte magic ``EDL1`` + uint32-LE payload
length) followed by a msgpack-encoded payload. The same framing is spoken by
the Python services and the native C++ runtime (``native/``), so either side
of any control-plane connection can be swapped for its native twin.

This replaces BOTH of the reference's control-plane transports — gRPC/
protobuf services (pod_server.proto, data_server.proto,
distill_discovery.proto) and the hand-rolled epoll JSON protocol with CRC
magic ``\\xCB\\xEF\\x00\\x00`` (python/edl/distill/redis/balance_server.py:
40-216) — with a single codegen-free protocol.

Payload conventions (by example, not schema):
  request:  {"i": <id>, "m": <method>, ...params}
  response: {"i": <id>, "ok": true, ...result}
  error:    {"i": <id>, "ok": false, "err": {"etype": ..., "detail": ...}}
  push:     {"w": <watch_id>, "ev": [...]}          (server-initiated)
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional

import msgpack

MAGIC = b"EDL1"
_HEADER = struct.Struct("<4sI")
HEADER_SIZE = _HEADER.size
MAX_FRAME = 512 * 1024 * 1024  # bound a corrupt length field


class WireError(Exception):
    pass


def pack_frame(payload: dict) -> bytes:
    body = msgpack.packb(payload, use_bin_type=True)
    return _HEADER.pack(MAGIC, len(body)) + body


def unpack_payload(body: bytes) -> dict:
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class FrameReader:
    """Incremental frame decoder for a nonblocking byte stream.

    Feed it whatever ``recv`` returned; it yields complete decoded payloads
    and buffers the remainder.
    """

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[dict]:
        self._buf.extend(data)
        out: List[dict] = []
        while True:
            payload = self._try_next()
            if payload is None:
                return out
            out.append(payload)

    def _try_next(self) -> Optional[dict]:
        if len(self._buf) < HEADER_SIZE:
            return None
        magic, length = _HEADER.unpack_from(self._buf, 0)
        if magic != MAGIC:
            raise WireError("bad frame magic %r" % magic)
        if length > MAX_FRAME:
            raise WireError("frame length %d exceeds limit" % length)
        end = HEADER_SIZE + length
        if len(self._buf) < end:
            return None
        body = bytes(self._buf[HEADER_SIZE:end])
        del self._buf[:end]
        return unpack_payload(body)


def read_frame_blocking(sock) -> dict:
    """Read exactly one frame from a blocking socket."""
    header = _recv_exact(sock, HEADER_SIZE)
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise WireError("bad frame magic %r" % magic)
    if length > MAX_FRAME:
        raise WireError("frame length %d exceeds limit" % length)
    return unpack_payload(_recv_exact(sock, length))


def _recv_exact(sock, n: int) -> bytes:
    chunks = bytearray()
    while len(chunks) < n:
        chunk = sock.recv(n - len(chunks))
        if not chunk:
            raise ConnectionError("peer closed during frame read")
        chunks.extend(chunk)
    return bytes(chunks)
